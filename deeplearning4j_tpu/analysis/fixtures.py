"""graftcheck fixture zoo — the graphs the gate's ``check`` stage verifies.

Two families:

* :func:`clean_fixtures` — representative clean graphs (the examples'
  SameDiff MLP, a symbolic-batch CNN, a symbolic-batch BERT-style encoder,
  a numpy-static shape chain, an ONNX-dialect import, and zoo networks).
  The committed ``check_baseline.json`` expects ZERO findings here; any
  finding is a regression in an op rule, an importer, or the checker.
* :func:`seeded_error_fixtures` — one graph per GC code with a planted
  bug, used by the suite (and docs/ANALYSIS.md) to pin each code's
  true-positive behavior.

Everything here is build-only: no jit, no execution — the fixtures stay
gate-cheap (<1s) even on CPU-only hosts.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, _Node


# ---------------------------------------------------------------------------
# clean graphs
# ---------------------------------------------------------------------------


def mlp_sym_batch() -> SameDiff:
    """The examples/samediff_training.py graph: symbolic-batch MLP."""
    r = np.random.RandomState(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(None, 8))
    labels = sd.placeholder("labels", shape=(None, 3))
    w0 = sd.var("w0", r.randn(8, 16).astype(np.float32) * 0.2)
    b0 = sd.var("b0", np.zeros(16, np.float32))
    w1 = sd.var("w1", r.randn(16, 3).astype(np.float32) * 0.2)
    h = sd.nn.relu(x @ w0 + b0)
    logits = h @ w1
    sd.loss.softmax_cross_entropy(logits, labels).rename("loss")
    logits.rename("logits")
    sd.graph_inputs, sd.graph_outputs = ["x", "labels"], ["logits", "loss"]
    return sd


def cnn_sym_batch() -> SameDiff:
    """Symbolic-batch conv/pool stack over the registry conv ops."""
    r = np.random.RandomState(1)
    sd = SameDiff()
    img = sd.placeholder("img", shape=(None, 28, 28, 1))
    w1 = sd.var("wc1", (r.randn(3, 3, 1, 8) * 0.1).astype(np.float32))
    w2 = sd.var("wc2", (r.randn(3, 3, 8, 16) * 0.1).astype(np.float32))
    c1 = sd.cnn.conv2d(img, w1, stride=1, padding="same")
    p1 = sd.cnn.max_pooling2d(sd.nn.relu(c1), kernel=2, stride=2)
    c2 = sd.cnn.conv2d(p1, w2, stride=1, padding="same")
    p2 = sd.cnn.avg_pooling2d(sd.nn.relu(c2), kernel=2, stride=2)
    p2.rename("features")
    sd.graph_inputs, sd.graph_outputs = ["img"], ["features"]
    return sd


def bert_encoder_sym_batch(layers: int = 2, seq: int = 128, d: int = 64,
                           ff: int = 128) -> SameDiff:
    """BERT-style encoder with a named symbolic batch dim — the
    ``placeholder(shape=(None, 128))`` acceptance graph. Attention is
    single-head (head splits need concrete reshape targets; the symbolic
    batch is what this fixture pins) with the full residual/layer-norm/
    gelu-FF block structure."""
    r = np.random.RandomState(2)
    sd = SameDiff()
    ids = sd.placeholder("ids", shape=(None, seq))
    mask = sd.placeholder("mask", shape=(None, seq))
    emb = sd.var("emb", (r.randn(512, d) * 0.02).astype(np.float32))
    pos = sd.var("pos", (r.randn(seq, d) * 0.02).astype(np.float32))
    x = sd.op("gather", emb, ids, axis=0) + pos

    scale = sd.constant("scale", np.float32(np.sqrt(d)))
    neg_big = sd.constant("neg_big", np.float32(-10000.0))
    one = sd.constant("one", np.float32(1.0))
    pen = (one - mask) * neg_big                      # (N, T)
    pen = sd._record("expand_dims", [pen], {"axis": 1})  # (N, 1, T)

    for i in range(layers):
        p = f"l{i}"
        wq = sd.var(f"{p}_wq", (r.randn(d, d) * 0.02).astype(np.float32))
        wk = sd.var(f"{p}_wk", (r.randn(d, d) * 0.02).astype(np.float32))
        wv = sd.var(f"{p}_wv", (r.randn(d, d) * 0.02).astype(np.float32))
        wo = sd.var(f"{p}_wo", (r.randn(d, d) * 0.02).astype(np.float32))
        g1 = sd.var(f"{p}_g1", np.ones(d, np.float32))
        b1 = sd.var(f"{p}_b1", np.zeros(d, np.float32))
        w_ff1 = sd.var(f"{p}_ff1", (r.randn(d, ff) * 0.02).astype(np.float32))
        w_ff2 = sd.var(f"{p}_ff2", (r.randn(ff, d) * 0.02).astype(np.float32))
        g2 = sd.var(f"{p}_g2", np.ones(d, np.float32))
        b2 = sd.var(f"{p}_b2", np.zeros(d, np.float32))

        q, k, v = x @ wq, x @ wk, x @ wv
        scores = (q @ k.transpose(0, 2, 1)) / scale
        probs = sd.nn.softmax(scores + pen, axis=-1)
        ctx = (probs @ v) @ wo
        x = sd.nn.layer_norm(x + ctx, g1, b1)
        h = sd.nn.gelu(x @ w_ff1)
        x = sd.nn.layer_norm(x + h @ w_ff2, g2, b2)

    cls_w = sd.var("cls_w", (r.randn(d, 2) * 0.02).astype(np.float32))
    sd.nn.softmax(x @ cls_w).rename("y")
    sd.graph_inputs, sd.graph_outputs = ["ids", "mask"], ["y"]
    return sd


def fused_graph_sym_batch(seq: int = 32, d: int = 64, heads: int = 4,
                          page: int = 8) -> SameDiff:
    """A graph built on the optimizer's fusion-target registry ops —
    ``dot_product_attention`` (incl. ``causal=``), ``fused_matmul_bias_act``
    and ``paged_decode_attention`` — with a named symbolic batch dim. The
    gate's ``check`` stage verifying this with ZERO findings proves the
    first-class analysis rules cover fused graphs natively: the
    ``jax.eval_shape`` probe cannot run over symbolic dims, so any rule
    regression surfaces as GC006 opacity or a phantom error here."""
    r = np.random.RandomState(5)
    hd = d // heads
    sd = SameDiff()
    q = sd.placeholder("q", shape=(None, heads, seq, hd))
    k = sd.placeholder("k", shape=(None, heads, seq, hd))
    v = sd.placeholder("v", shape=(None, heads, seq, hd))
    mask = sd.placeholder("mask", shape=(None, 1, 1, seq))
    att = sd.op("dot_product_attention", q, k, v, mask, scaled=True)
    catt = sd.op("dot_product_attention", q, k, v, scaled=True, causal=True)
    x = sd.placeholder("x", shape=(None, d))
    w1 = sd.var("w1", (r.randn(d, d) * 0.05).astype(np.float32))
    b1 = sd.var("b1", np.zeros(d, np.float32))
    h = sd.op("fused_matmul_bias_act", x, w1, b1, activation="gelu_exact")
    h.rename("h")
    att.rename("att")
    catt.rename("causal_att")
    # decode tier: one query token per slot against a block-paged KV cache
    dq = sd.placeholder("dq", shape=(None, heads, hd))
    kp = sd.var("k_pages", (r.randn(6, page, heads, hd) * 0.1)
                .astype(np.float32))
    vp = sd.var("v_pages", (r.randn(6, page, heads, hd) * 0.1)
                .astype(np.float32))
    pt = sd.placeholder("page_table", shape=(None, 3), dtype=np.int32)
    sl = sd.placeholder("seq_lens", shape=(None,), dtype=np.int32)
    sd.op("paged_decode_attention", dq, kp, vp, pt, sl).rename("decoded")
    sd.graph_inputs = ["q", "k", "v", "mask", "x", "dq", "page_table",
                       "seq_lens"]
    sd.graph_outputs = ["att", "causal_att", "h", "decoded"]
    return sd


def tuned_kernels_sym_batch(d: int = 128) -> SameDiff:
    """The PR-9 kernel set as a symbolic-batch graph: ``fused_layer_norm``
    (+gelu epilogue), the int8 serving matmul (``quantize_int8`` →
    ``matmul_int8``) and a ``fused_updater_step`` leaf. Verifying this with
    ZERO findings proves the first-class rules cover the new registry ops
    natively — no ``jax.eval_shape`` probe fallback (which cannot run over
    the symbolic batch dim)."""
    r = np.random.RandomState(9)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(None, d))
    g = sd.var("ln_g", np.ones(d, np.float32))
    b = sd.var("ln_b", np.zeros(d, np.float32))
    h = sd.op("fused_layer_norm", x, g, b, axis=-1, eps=1e-5,
              activation="gelu")
    w = sd.var("w", (r.randn(d, d) * d ** -0.5).astype(np.float32))
    # the keepdims (1, N) scale straight out of quantize: the matmul_int8
    # rule and impls both accept it — no reshape glue needed
    wq, ws = sd.op("quantize_int8", w, axis=0, n_out=2)
    sd.op("matmul_int8", h, wq, ws).rename("y")
    # one fused optimizer leaf (concrete shapes — updater state has no
    # batch dim); Adam: state rides sorted as (m, v)
    p = sd.var("p", (r.randn(d) * 0.1).astype(np.float32))
    gr = sd.var("grad", (r.randn(d) * 0.01).astype(np.float32))
    m0 = sd.var("m0", np.zeros(d, np.float32))
    v0 = sd.var("v0", np.zeros(d, np.float32))
    lr = sd.constant(np.float32(1e-3))
    step = sd.constant(np.float32(0.0))
    new_p, _m1, _v1 = sd.op("fused_updater_step", p, gr, lr, step, m0, v0,
                            kind="Adam", n_out=3)
    new_p.rename("new_p")
    sd.graph_inputs, sd.graph_outputs = ["x"], ["y", "new_p"]
    return sd


def shape_chain() -> SameDiff:
    """numpy-static shape arithmetic: shape_of → unstack → stack →
    reshape_dynamic — the constant-env surface."""
    sd = SameDiff()
    x = sd.var("x", np.ones((6, 4), np.float32))
    s = sd.op("shape_of", x)
    a, b = sd.op("unstack", s, n_out=2)
    tgt = sd.op("stack", b, a)
    sd.op("reshape_dynamic", x, tgt).rename("y")
    sd.graph_inputs, sd.graph_outputs = [], ["y"]
    return sd


def onnx_mini_import() -> SameDiff:
    """A small ONNX-dialect graph (symbolic batch) lowered through the
    real importer mappers + IR walker — exercises the full
    import-then-check path without protobuf bytes."""
    from deeplearning4j_tpu.imports.ir import IRGraph, IRNode
    from deeplearning4j_tpu.imports.onnx_import import OnnxImporter

    r = np.random.RandomState(3)
    init = {
        "w0": (r.randn(8, 16) * 0.2).astype(np.float32),
        "b0": np.zeros(16, np.float32),
        "w1": (r.randn(16, 3) * 0.2).astype(np.float32),
    }
    nodes = [
        IRNode("mm0", "MatMul", ["x", "w0"], ["mm0"]),
        IRNode("a0", "Add", ["mm0", "b0"], ["a0"]),
        IRNode("r0", "Relu", ["a0"], ["r0"]),
        IRNode("mm1", "MatMul", ["r0", "w1"], ["mm1"]),
        IRNode("y", "Softmax", ["mm1"], ["y"], attrs={"axis": -1}),
    ]
    ir = IRGraph(nodes=nodes, initializers=init,
                 inputs=[("x", (None, 8))], outputs=["y"], name="onnx")
    return OnnxImporter().run_import(ir)


def zoo_networks() -> List[Tuple[str, Any]]:
    """Layer-level zoo graphs for check_network (built, not trained)."""
    from deeplearning4j_tpu import models, nn
    from deeplearning4j_tpu.nn.graph import (
        ComputationGraph, ElementWiseVertex, graph_builder)

    lenet = models.LeNet(num_classes=10)
    residual = ComputationGraph(
        graph_builder().seed(0)
        .add_inputs("in")
        .set_input_types(**{"in": nn.InputType.feed_forward(6)})
        .add_layer("d", nn.DenseLayer(n_out=6, activation="relu"), "in")
        .add_vertex("add", ElementWiseVertex(op="add"), "d", "in")
        .add_layer("out", nn.OutputLayer(n_out=2, activation="softmax",
                                         loss="mcxent"), "add")
        .set_outputs("out").build())
    return [("net/lenet", lenet), ("net/residual_graph", residual)]


def clean_fixtures() -> List[Tuple[str, Any]]:
    """(name, SameDiff-or-network) — the gate's zero-findings surface."""
    out: List[Tuple[str, Any]] = [
        ("zoo/mlp_sym_batch", mlp_sym_batch()),
        ("zoo/cnn_sym_batch", cnn_sym_batch()),
        ("zoo/bert_encoder_sym_batch", bert_encoder_sym_batch()),
        ("zoo/fused_graph_sym_batch", fused_graph_sym_batch()),
        ("zoo/tuned_kernels_sym_batch", tuned_kernels_sym_batch()),
        ("zoo/shape_chain", shape_chain()),
        ("onnx/mini_mlp", onnx_mini_import()),
    ]
    out.extend(zoo_networks())
    return out


# ---------------------------------------------------------------------------
# seeded errors — one per GC code (docs/ANALYSIS.md examples)
# ---------------------------------------------------------------------------


def seeded_error_fixtures() -> List[Tuple[str, str, SameDiff]]:
    """(expected_code, name, graph) triples. Planted with sd internals
    where the public API already refuses the mistake (the checker's job is
    graphs that arrive broken — deserialization, importer bugs)."""
    out: List[Tuple[str, str, SameDiff]] = []

    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._record("transpose", [x], {"axes": (0, 1, 2)})
    out.append(("GC001", "seeded/rank_mismatch", sd))

    sd = SameDiff()
    a = sd.placeholder("a", (2, 3))
    b = sd.placeholder("b", (4, 5))
    a + b
    out.append(("GC002", "seeded/broadcast_failure", sd))

    sd = SameDiff()
    a = sd.var("i32", np.ones(3, np.int32))
    b = sd.var("u32", np.ones(3, np.uint32))
    sd._record("add", [a, b])
    out.append(("GC003", "seeded/promotion_surprise", sd))

    sd = SameDiff()
    sd.placeholder("x", (3,))
    sd._nodes.append(_Node("add", ["x", "ghost"], {}, ["dangling_out"]))
    out.append(("GC004", "seeded/dangling_input", sd))

    sd = SameDiff()
    x = sd.placeholder("x", (4, 3))
    x.reshape(5, 3)
    out.append(("GC005", "seeded/reshape_count", sd))

    sd = SameDiff()
    x = sd.placeholder("x", (None, 8))
    sd.op("top_k", x, k=2, n_out=2)
    out.append(("GC006", "seeded/unknown_op", sd))

    return out
