"""graftcheck — abstract shape/dtype interpreter over SameDiff graphs.

Verifies whole graphs BEFORE the ``jax.jit`` trace: symbolic shapes
(concrete ints + named batch dims) and dtypes propagate through per-op
inference rules, so a bad import rule or optimizer pass surfaces as a
GC-coded finding with node provenance at graph-build time instead of an
opaque XLA tracer error hundreds of nodes away (docs/ANALYSIS.md).

Entry points:

* ``SameDiff.check()`` / ``SameDiff(validate=True)`` — user surface
* ``check_samediff(sd)`` / ``check_network(net)`` — direct calls
* every importer (ONNX / TF / IR / Keras) runs the check automatically
* ``autodiff/optimize.py`` asserts pass-pipeline shape/dtype invariance
  through the same interpreter
* ``python -m deeplearning4j_tpu.analysis`` — the gate's ``check`` stage
  over the fixture zoo, baselined in ``check_baseline.json``
"""

from deeplearning4j_tpu.analysis.report import (
    CheckReport, GC_CODES, GraphCheckError, PassInvariantError)
from deeplearning4j_tpu.analysis.interpreter import (
    check_samediff, infer_nodes, seed_avals)
from deeplearning4j_tpu.analysis.network import check_network
from deeplearning4j_tpu.analysis.values import AVal, Dim

# the one-call spelling used by importers and docs
check = check_samediff

__all__ = [
    "AVal", "CheckReport", "Dim", "GC_CODES", "GraphCheckError",
    "PassInvariantError", "check", "check_network", "check_samediff",
    "infer_nodes", "seed_avals",
]
