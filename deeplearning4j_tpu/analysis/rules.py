"""graftcheck per-op inference rules over the GRAPH_OPS surface.

Each rule maps ``(node, in_avals, emit)`` to a list of output
:class:`AVal`s, emitting GC-coded findings through ``emit(code, message)``
(the interpreter prefixes node provenance and fills severity from
``report.GC_CODES``). Soundness contract: error findings only on
*provable* mismatches (concrete ints disagree); symbolic (:class:`Dim`)
and unknown entries degrade the output, never fire errors — a
``placeholder(shape=(None, 128))`` batch must flow through the whole BERT
graph with zero findings.

Ops not covered here fall back to the interpreter's ``jax.eval_shape``
probe (concrete shapes only) and then to the sound unknown + GC006 path.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.analysis.broadcast import (
    BroadcastError, broadcast_shapes, is_float_dtype, promote_dtypes,
    promotion_surprise)
from deeplearning4j_tpu.analysis.values import (
    AVal, DimEntry, Shape, dims_provably_unequal, fmt_shape)

RULES: Dict[str, Callable[..., List[AVal]]] = {}

_F32 = np.dtype(np.float32)
_I32 = np.dtype(np.int32)


def op_rule(*names: str):
    def deco(fn):
        for n in names:
            RULES[n] = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# small helpers
# ---------------------------------------------------------------------------


def _norm_axis(axis: int, rank: int) -> Optional[int]:
    """Normalize a (possibly negative) axis; None when out of range."""
    ax = axis + rank if axis < 0 else axis
    return ax if 0 <= ax < rank else None


def _shapes_str(ins: Sequence[AVal]) -> str:
    return " and ".join(fmt_shape(a.shape) for a in ins)


def _float_result(dt: Optional[np.dtype]) -> Optional[np.dtype]:
    """dtype of a float-producing unary (exp/log/…): floats pass through,
    ints/bools become float32 (jax x32 default), unknown stays unknown."""
    if dt is None:
        return None
    return dt if is_float_dtype(dt) else _F32


def _broadcast_or_emit(ins: Sequence[AVal], emit, what: str) -> Shape:
    try:
        return broadcast_shapes([a.shape for a in ins])
    except BroadcastError as e:
        emit("GC002", f"{what}: operands {_shapes_str(ins)} do not "
                      f"broadcast ({e.detail})")
        return None


def _maybe_promo_warn(ins: Sequence[AVal], emit) -> None:
    reason = promotion_surprise([a.dtype for a in ins])
    if reason:
        emit("GC003", f"dtype promotion surprise: {reason}")


def _prod(entries) -> Optional[int]:
    out = 1
    for d in entries:
        if not isinstance(d, int):
            return None
        out *= d
    return out


# ---------------------------------------------------------------------------
# elementwise
# ---------------------------------------------------------------------------


@op_rule("add", "sub", "mul", "maximum", "minimum", "pow", "floormod",
         "squared_difference")
def _ew_binary(node, ins, emit):
    shape = _broadcast_or_emit(ins[:2], emit, f"'{node.op}'")
    _maybe_promo_warn(ins[:2], emit)
    return [AVal(shape, promote_dtypes([a.dtype for a in ins[:2]]))]


@op_rule("div")
def _ew_div(node, ins, emit):
    shape = _broadcast_or_emit(ins[:2], emit, "'div'")
    _maybe_promo_warn(ins[:2], emit)
    dt = promote_dtypes([a.dtype for a in ins[:2]])
    if dt is not None and not is_float_dtype(dt):
        dt = _F32  # true division promotes integral operands
    return [AVal(shape, dt)]


@op_rule("gt", "lt", "gte", "lte", "eq", "neq")
def _ew_compare(node, ins, emit):
    # GRAPH_OPS comparisons cast the bool result to float32
    shape = _broadcast_or_emit(ins[:2], emit, f"'{node.op}'")
    return [AVal(shape, _F32)]


_PRESERVING_UNARY = (
    "neg", "abs", "sign", "floor", "ceil", "round", "square", "relu",
    "relu6", "leakyrelu", "hardtanh", "clip_by_value_graph",
    "dropout_graph", "zeros_like", "ones_like", "identity", "cumsum",
)

_FLOAT_UNARY = (
    "exp", "log", "log1p", "sqrt", "rsqrt", "sin", "cos", "tan", "asin",
    "acos", "atan", "sinh", "cosh", "tanh", "erf", "sigmoid", "softplus",
    "softsign", "swish", "mish", "gelu", "elu", "selu", "hardsigmoid",
    "reciprocal",
)


@op_rule(*_PRESERVING_UNARY)
def _unary_preserve(node, ins, emit):
    return [AVal(ins[0].shape, ins[0].dtype)]


@op_rule(*_FLOAT_UNARY)
def _unary_float(node, ins, emit):
    return [AVal(ins[0].shape, _float_result(ins[0].dtype))]


@op_rule("softmax", "log_softmax")
def _softmax(node, ins, emit):
    axis = int(node.kwargs.get("axis", -1))
    r = ins[0].rank
    if r is not None and _norm_axis(axis, r) is None:
        emit("GC001", f"softmax axis {axis} out of range for rank {r} "
                      f"input {fmt_shape(ins[0].shape)}")
    return [AVal(ins[0].shape, _float_result(ins[0].dtype))]


@op_rule("cast")
def _cast(node, ins, emit):
    try:
        dt = np.dtype(node.kwargs.get("dtype"))
    except TypeError:
        dt = None
    return [AVal(ins[0].shape, dt)]


@op_rule("where", "select")
def _where(node, ins, emit):
    shape = _broadcast_or_emit(ins[:3], emit, f"'{node.op}'")
    _maybe_promo_warn(ins[1:3], emit)
    return [AVal(shape, promote_dtypes([a.dtype for a in ins[1:3]]))]


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------


def _matmul_shape(a: Shape, b: Shape, emit, what: str) -> Shape:
    """numpy matmul semantics over symbolic shapes."""
    if a is None or b is None:
        return None
    if len(a) == 0 or len(b) == 0:
        emit("GC001", f"{what}: matmul operand is 0-d "
                      f"({fmt_shape(a)} @ {fmt_shape(b)})")
        return None
    av = (1,) + tuple(a) if len(a) == 1 else tuple(a)
    bv = tuple(b) + (1,) if len(b) == 1 else tuple(b)
    if dims_provably_unequal(av[-1], bv[-2]):
        emit("GC002", f"{what}: contraction mismatch — inner dims "
                      f"{av[-1]} vs {bv[-2]} ({fmt_shape(a)} @ {fmt_shape(b)})")
        return None
    try:
        batch = broadcast_shapes([av[:-2] or (), bv[:-2] or ()])
    except BroadcastError as e:
        emit("GC002", f"{what}: batch dims do not broadcast ({e.detail}) "
                      f"({fmt_shape(a)} @ {fmt_shape(b)})")
        return None
    if batch is None:
        return None
    out = tuple(batch) + (av[-2], bv[-1])
    if len(a) == 1:
        out = out[:-2] + (out[-1],)
    if len(b) == 1:
        out = out[:-1]
    return out


def _swap_last2(s: Shape, emit, what: str) -> Shape:
    if s is None:
        return None
    if len(s) < 2:
        emit("GC001", f"{what}: transpose flag needs rank >= 2, got "
                      f"{fmt_shape(s)}")
        return None
    return s[:-2] + (s[-1], s[-2])


@op_rule("mmul")
def _mmul(node, ins, emit):
    a, b = ins[0].shape, ins[1].shape
    if node.kwargs.get("transpose_a"):
        a = _swap_last2(a, emit, "'mmul'")
    if node.kwargs.get("transpose_b"):
        b = _swap_last2(b, emit, "'mmul'")
    _maybe_promo_warn(ins[:2], emit)
    return [AVal(_matmul_shape(a, b, emit, "'mmul'"),
                 promote_dtypes([ins[0].dtype, ins[1].dtype]))]


@op_rule("matrix_transpose")
def _matrix_transpose(node, ins, emit):
    return [AVal(_swap_last2(ins[0].shape, emit, "'matrix_transpose'"),
                 ins[0].dtype)]


@op_rule("linear")
def _linear(node, ins, emit):
    _maybe_promo_warn(ins[:2], emit)
    shape = _matmul_shape(ins[0].shape, ins[1].shape, emit, "'linear'")
    if len(ins) > 2 and shape is not None:
        try:
            shape = broadcast_shapes([shape, ins[2].shape])
        except BroadcastError as e:
            emit("GC002", f"'linear': bias {fmt_shape(ins[2].shape)} does "
                          f"not broadcast onto {fmt_shape(shape)} ({e.detail})")
            shape = None
    return [AVal(shape, promote_dtypes([a.dtype for a in ins[:2]]))]


@op_rule("tensordot")
def _tensordot(node, ins, emit):
    axes = node.kwargs.get("axes")
    a, b = ins[0].shape, ins[1].shape
    if a is None or b is None:
        return [AVal(None, promote_dtypes([ins[0].dtype, ins[1].dtype]))]
    if isinstance(axes, int):
        ax_a = list(range(len(a) - axes, len(a)))
        ax_b = list(range(axes))
    else:
        try:
            ax_a = [int(x) for x in np.atleast_1d(axes[0])]
            ax_b = [int(x) for x in np.atleast_1d(axes[1])]
        except (TypeError, IndexError):
            return [AVal(None, promote_dtypes([ins[0].dtype, ins[1].dtype]))]
    ax_a = [x + len(a) if x < 0 else x for x in ax_a]
    ax_b = [x + len(b) if x < 0 else x for x in ax_b]
    if any(not 0 <= x < len(a) for x in ax_a) or \
            any(not 0 <= x < len(b) for x in ax_b):
        emit("GC001", f"'tensordot': axes {axes} out of range for "
                      f"{_shapes_str(ins[:2])}")
        return [AVal()]
    for x, y in zip(ax_a, ax_b):
        if dims_provably_unequal(a[x], b[y]):
            emit("GC002", f"'tensordot': contracted dims {a[x]} vs {b[y]} "
                          f"differ ({_shapes_str(ins[:2])}, axes={axes})")
    shape = tuple(d for i, d in enumerate(a) if i not in ax_a) + \
        tuple(d for i, d in enumerate(b) if i not in ax_b)
    return [AVal(shape, promote_dtypes([ins[0].dtype, ins[1].dtype]))]


# ---------------------------------------------------------------------------
# shape / layout
# ---------------------------------------------------------------------------


def _reshape_target(src: AVal, target, emit, what: str) -> Shape:
    tgt = [int(d) for d in target]
    n_minus = sum(1 for d in tgt if d < 0)
    if n_minus > 1:
        emit("GC001", f"{what}: more than one -1 in target shape {tgt}")
        return None
    src_n = src.num_elements()
    tgt_known = _prod(d for d in tgt if d >= 0)
    if n_minus == 0:
        if src_n is not None and src_n != tgt_known:
            emit("GC005", f"{what}: cannot reshape {fmt_shape(src.shape)} "
                          f"({src_n} elements) to {tuple(tgt)} "
                          f"({tgt_known} elements)")
            return None
        return tuple(tgt)
    # one -1: infer when the source count is concrete
    if src_n is None or tgt_known in (None, 0):
        return tuple(d if d >= 0 else None for d in tgt)
    if src_n % tgt_known != 0:
        emit("GC005", f"{what}: cannot reshape {fmt_shape(src.shape)} "
                      f"({src_n} elements) to {tuple(tgt)} "
                      f"(-1 is not integral: {src_n} / {tgt_known})")
        return None
    return tuple(d if d >= 0 else src_n // tgt_known for d in tgt)


@op_rule("reshape")
def _reshape(node, ins, emit):
    target = node.kwargs.get("shape")
    if target is None:
        return [AVal(None, ins[0].dtype)]
    return [AVal(_reshape_target(ins[0], target, emit, "'reshape'"),
                 ins[0].dtype)]


@op_rule("reshape_dynamic")
def _reshape_dyn(node, ins, emit):
    tgt = ins[1].value
    if tgt is not None:
        return [AVal(_reshape_target(ins[0], np.asarray(tgt).reshape(-1),
                                     emit, "'reshape_dynamic'"),
                     ins[0].dtype)]
    ts = ins[1].shape
    if ts is not None and len(ts) == 1 and isinstance(ts[0], int):
        return [AVal((None,) * ts[0], ins[0].dtype)]
    return [AVal(None, ins[0].dtype)]


@op_rule("transpose", "permute")
def _transpose(node, ins, emit):
    axes = node.kwargs.get("axes")
    s = ins[0].shape
    if axes is None:
        return [AVal(None if s is None else tuple(reversed(s)),
                     ins[0].dtype)]
    axes = tuple(int(a) for a in axes)
    if s is None:
        return [AVal(None, ins[0].dtype)]
    r = len(s)
    norm = [_norm_axis(a, r) for a in axes]
    if len(axes) != r or None in norm or sorted(norm) != list(range(r)):
        emit("GC001", f"'{node.op}': axes {axes} is not a permutation of "
                      f"rank-{r} input {fmt_shape(s)}")
        return [AVal(None, ins[0].dtype)]
    return [AVal(tuple(s[a] for a in norm), ins[0].dtype)]


@op_rule("expand_dims")
def _expand_dims(node, ins, emit):
    s = ins[0].shape
    axis = int(node.kwargs.get("axis", 0))
    if s is None:
        return [AVal(None, ins[0].dtype)]
    r = len(s)
    ax = axis + r + 1 if axis < 0 else axis
    if not 0 <= ax <= r:
        emit("GC001", f"'expand_dims': axis {axis} out of range for "
                      f"rank-{r} input {fmt_shape(s)}")
        return [AVal(None, ins[0].dtype)]
    return [AVal(s[:ax] + (1,) + s[ax:], ins[0].dtype)]


@op_rule("squeeze")
def _squeeze(node, ins, emit):
    s = ins[0].shape
    axis = node.kwargs.get("axis")
    if s is None:
        return [AVal(None, ins[0].dtype)]
    r = len(s)
    if axis is None:
        if all(isinstance(d, int) for d in s):
            return [AVal(tuple(d for d in s if d != 1), ins[0].dtype)]
        return [AVal(None, ins[0].dtype)]  # symbolic dims might be 1
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    norm = []
    for a in axes:
        na = _norm_axis(int(a), r)
        if na is None:
            emit("GC001", f"'squeeze': axis {a} out of range for rank-{r} "
                          f"input {fmt_shape(s)}")
            return [AVal(None, ins[0].dtype)]
        if isinstance(s[na], int) and s[na] != 1:
            emit("GC001", f"'squeeze': axis {a} has size {s[na]} != 1 in "
                          f"{fmt_shape(s)}")
            return [AVal(None, ins[0].dtype)]
        norm.append(na)
    return [AVal(tuple(d for i, d in enumerate(s) if i not in norm),
                 ins[0].dtype)]


@op_rule("concat")
def _concat(node, ins, emit):
    axis = int(node.kwargs.get("axis", 0))
    ranks = {a.rank for a in ins if a.rank is not None}
    if len(ranks) > 1:
        emit("GC001", f"'concat': inputs of different ranks "
                      f"{_shapes_str(ins)}")
        return [AVal(None, promote_dtypes([a.dtype for a in ins]))]
    _maybe_promo_warn(ins, emit)
    dt = promote_dtypes([a.dtype for a in ins])
    if not ranks:
        return [AVal(None, dt)]
    r = ranks.pop()
    if r == 0:
        emit("GC001", "'concat': zero-dimensional inputs cannot be "
                      "concatenated")
        return [AVal(None, dt)]
    ax = _norm_axis(axis, r)
    if ax is None:
        emit("GC001", f"'concat': axis {axis} out of range for rank {r}")
        return [AVal(None, dt)]
    out: List[DimEntry] = []
    for i in range(r):
        if i == ax:
            total = 0
            for a in ins:
                d = None if a.shape is None else a.shape[i]
                if isinstance(d, int) and total is not None:
                    total += d
                else:
                    total = None
            out.append(total)
            continue
        entry: DimEntry = None
        for a in ins:
            d = None if a.shape is None else a.shape[i]
            if d is None:
                continue
            if entry is None:
                entry = d
            elif dims_provably_unequal(entry, d):
                emit("GC002", f"'concat': non-axis dim {i} differs "
                              f"({entry} vs {d}) across {_shapes_str(ins)}")
                return [AVal(None, dt)]
            elif isinstance(d, int):
                entry = d  # prefer concrete over symbolic
        out.append(entry)
    return [AVal(tuple(out), dt)]


@op_rule("stack")
def _stack(node, ins, emit):
    axis = int(node.kwargs.get("axis", 0))
    base: Shape = None
    for a in ins:
        if a.shape is None:
            continue
        if base is None:
            base = a.shape
        elif len(base) != len(a.shape) or any(
                dims_provably_unequal(x, y) for x, y in zip(base, a.shape)):
            emit("GC002", f"'stack': inputs must share one shape, got "
                          f"{_shapes_str(ins)}")
            return [AVal(None, promote_dtypes([a.dtype for a in ins]))]
    dt = promote_dtypes([a.dtype for a in ins])
    if base is None:
        return [AVal(None, dt)]
    r = len(base) + 1
    ax = _norm_axis(axis, r)
    if ax is None:
        emit("GC001", f"'stack': axis {axis} out of range for result "
                      f"rank {r}")
        return [AVal(None, dt)]
    return [AVal(base[:ax] + (len(ins),) + base[ax:], dt)]


@op_rule("unstack")
def _unstack(node, ins, emit):
    s = ins[0].shape
    axis = int(node.kwargs.get("axis", 0))
    n_out = len(node.outputs)
    if s is None:
        return [AVal(None, ins[0].dtype) for _ in range(n_out)]
    ax = _norm_axis(axis, len(s))
    if ax is None:
        emit("GC001", f"'unstack': axis {axis} out of range for "
                      f"{fmt_shape(s)}")
        return [AVal(None, ins[0].dtype) for _ in range(n_out)]
    if isinstance(s[ax], int) and s[ax] != n_out:
        emit("GC001", f"'unstack': axis {axis} has size {s[ax]} but the "
                      f"node declares {n_out} outputs")
    rest = s[:ax] + s[ax + 1:]
    return [AVal(rest, ins[0].dtype) for _ in range(n_out)]


@op_rule("unstack_first")
def _unstack_first(node, ins, emit):
    s = ins[0].shape
    if s is not None and len(s) == 0:
        emit("GC001", "'unstack_first': input is 0-d")
        return [AVal()]
    return [AVal(None if s is None else s[1:], ins[0].dtype)]


@op_rule("gather")
def _gather(node, ins, emit):
    params, idx = ins[0], ins[1]
    axis = int(node.kwargs.get("axis", 0))
    if params.shape is None:
        return [AVal(None, params.dtype)]
    ax = _norm_axis(axis, len(params.shape))
    if ax is None:
        emit("GC001", f"'gather': axis {axis} out of range for "
                      f"{fmt_shape(params.shape)}")
        return [AVal(None, params.dtype)]
    if idx.shape is None:
        return [AVal(None, params.dtype)]
    return [AVal(params.shape[:ax] + idx.shape + params.shape[ax + 1:],
                 params.dtype)]


@op_rule("tile")
def _tile(node, ins, emit):
    s = ins[0].shape
    reps = node.kwargs.get("reps")
    if s is None or reps is None:
        return [AVal(None, ins[0].dtype)]
    reps = [int(r) for r in np.atleast_1d(reps)]
    r = max(len(s), len(reps))
    full_s = (1,) * (r - len(s)) + tuple(s)
    full_r = [1] * (r - len(reps)) + reps
    out = tuple(d * m if isinstance(d, int) else (d if m == 1 else None)
                for d, m in zip(full_s, full_r))
    return [AVal(out, ins[0].dtype)]


@op_rule("pad")
def _pad(node, ins, emit):
    s = ins[0].shape
    paddings = node.kwargs.get("paddings")
    if s is None or paddings is None:
        return [AVal(None, ins[0].dtype)]
    try:
        pads = [(int(lo), int(hi)) for lo, hi in paddings]
    except (TypeError, ValueError):
        return [AVal(None, ins[0].dtype)]
    if len(pads) != len(s):
        emit("GC001", f"'pad': {len(pads)} padding pairs for rank-{len(s)} "
                      f"input {fmt_shape(s)}")
        return [AVal(None, ins[0].dtype)]
    out = tuple(d + lo + hi if isinstance(d, int) else
                (d if lo == 0 and hi == 0 else None)
                for d, (lo, hi) in zip(s, pads))
    return [AVal(out, ins[0].dtype)]


@op_rule("slice")
def _slice(node, ins, emit):
    s = ins[0].shape
    begin = node.kwargs.get("begin")
    size = node.kwargs.get("size")
    if s is None or size is None:
        return [AVal(None, ins[0].dtype)]
    size = [int(x) for x in size]
    if len(size) != len(s):
        emit("GC001", f"'slice': size has {len(size)} entries for "
                      f"rank-{len(s)} input {fmt_shape(s)}")
        return [AVal(None, ins[0].dtype)]
    for i, (d, sz) in enumerate(zip(s, size)):
        if isinstance(d, int) and sz > d:
            emit("GC001", f"'slice': size[{i}]={sz} exceeds input dim {d} "
                          f"in {fmt_shape(s)}")
            return [AVal(None, ins[0].dtype)]
    del begin  # dynamic_slice clamps the start; size alone fixes the shape
    return [AVal(tuple(size), ins[0].dtype)]


@op_rule("strided_slice")
def _strided_slice(node, ins, emit):
    s = ins[0].shape
    begin = node.kwargs.get("begin")
    end = node.kwargs.get("end")
    strides = node.kwargs.get("strides")
    if s is None or begin is None or end is None:
        return [AVal(None, ins[0].dtype)]
    begin = [int(b) for b in begin]
    end = [int(e) for e in end]
    strides = [int(x) for x in strides] if strides else [1] * len(begin)
    if len(begin) > len(s):
        emit("GC001", f"'strided_slice': {len(begin)} slice specs for "
                      f"rank-{len(s)} input {fmt_shape(s)}")
        return [AVal(None, ins[0].dtype)]
    out: List[DimEntry] = []
    for i, d in enumerate(s):
        if i >= len(begin):
            out.append(d)
        elif isinstance(d, int):
            out.append(len(range(*slice(begin[i], end[i],
                                        strides[i]).indices(d))))
        else:
            out.append(None)  # clamped bounds depend on the symbolic dim
    return [AVal(tuple(out), ins[0].dtype)]


@op_rule("flatten_from")
def _flatten_from(node, ins, emit):
    s = ins[0].shape
    axis = int(node.kwargs.get("axis", 1))
    if s is None:
        return [AVal(None, ins[0].dtype)]
    ax = axis + len(s) if axis < 0 else axis
    if not 0 <= ax <= len(s):
        emit("GC001", f"'flatten_from': axis {axis} out of range for "
                      f"{fmt_shape(s)}")
        return [AVal(None, ins[0].dtype)]

    def seg(entries):
        if len(entries) == 1:
            return entries[0]
        return _prod(entries)

    return [AVal((seg(s[:ax]) if ax else 1, seg(s[ax:]) if ax < len(s) else 1),
                 ins[0].dtype)]


@op_rule("broadcast_to")
def _broadcast_to(node, ins, emit):
    target = node.kwargs.get("shape")
    if target is None:
        return [AVal(None, ins[0].dtype)]
    tgt = tuple(int(d) for d in target)
    s = ins[0].shape
    if s is not None:
        if len(s) > len(tgt):
            emit("GC002", f"'broadcast_to': input {fmt_shape(s)} has higher "
                          f"rank than target {tgt}")
        else:
            for i in range(1, len(s) + 1):
                d = s[-i]
                if isinstance(d, int) and d != 1 and d != tgt[-i]:
                    emit("GC002", f"'broadcast_to': dim {d} does not "
                                  f"broadcast to {tgt[-i]} "
                                  f"({fmt_shape(s)} -> {tgt})")
                    break
    return [AVal(tgt, ins[0].dtype)]


@op_rule("shape_of")
def _shape_of(node, ins, emit):
    r = ins[0].rank
    # impl returns numpy int32 (int64 only for >2**31 dims — rare)
    return [AVal(None if r is None else (r,), _I32)]


@op_rule("size")
def _size(node, ins, emit):
    return [AVal((), _I32)]


@op_rule("one_hot_graph")
def _one_hot(node, ins, emit):
    depth = int(node.kwargs.get("depth", 0))
    s = ins[0].shape
    return [AVal(None if s is None else s + (depth,), _F32)]


@op_rule("fill")
def _fill(node, ins, emit):
    shape = node.kwargs.get("shape")
    try:
        dt = np.dtype(node.kwargs.get("dtype", np.float32))
    except TypeError:
        dt = _F32
    return [AVal(None if shape is None else tuple(int(d) for d in shape),
                 dt)]


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def _reduce_shape(s: Shape, axes, keepdims: bool, emit, what: str) -> Shape:
    if s is None:
        return None
    r = len(s)
    if axes is None:
        return (1,) * r if keepdims else ()
    norm = []
    for a in axes:
        na = _norm_axis(int(a), r)
        if na is None:
            emit("GC001", f"{what}: axis {a} out of range for rank-{r} "
                          f"input {fmt_shape(s)}")
            return None
        norm.append(na)
    if keepdims:
        return tuple(1 if i in norm else d for i, d in enumerate(s))
    return tuple(d for i, d in enumerate(s) if i not in norm)


@op_rule("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
         "reduce_prod", "reduce_std", "reduce_var")
def _reduce(node, ins, emit):
    axes = node.kwargs.get("axes") or None
    keep = bool(node.kwargs.get("keepdims", False))
    shape = _reduce_shape(ins[0].shape, axes, keep, emit, f"'{node.op}'")
    dt = ins[0].dtype
    if node.op in ("reduce_mean", "reduce_std", "reduce_var"):
        dt = _float_result(dt)
    return [AVal(shape, dt)]


@op_rule("argmax", "argmin")
def _argminmax(node, ins, emit):
    axis = int(node.kwargs.get("axis", -1))
    s = ins[0].shape
    if s is None:
        return [AVal(None, _I32)]
    ax = _norm_axis(axis, len(s))
    if ax is None:
        emit("GC001", f"'{node.op}': axis {axis} out of range for "
                      f"{fmt_shape(s)}")
        return [AVal(None, _I32)]
    return [AVal(s[:ax] + s[ax + 1:], _I32)]


@op_rule("norm2")
def _norm2(node, ins, emit):
    axes = node.kwargs.get("axes") or None
    shape = _reduce_shape(ins[0].shape, axes, False, emit, "'norm2'")
    return [AVal(shape, _float_result(ins[0].dtype))]


# ---------------------------------------------------------------------------
# nn composites + losses
# ---------------------------------------------------------------------------


@op_rule("layer_norm_graph")
def _layer_norm(node, ins, emit):
    x = ins[0]
    if len(ins) > 1 and x.shape is not None and ins[1].shape is not None:
        try:
            broadcast_shapes([x.shape, ins[1].shape])
        except BroadcastError as e:
            emit("GC002", f"'layer_norm_graph': gain "
                          f"{fmt_shape(ins[1].shape)} does not broadcast "
                          f"onto x {fmt_shape(x.shape)} ({e.detail})")
    return [AVal(x.shape, _float_result(x.dtype))]


@op_rule("batch_norm_graph")
def _batch_norm(node, ins, emit):
    return [AVal(ins[0].shape, _float_result(ins[0].dtype))]


_SCALAR_LOSSES = ("softmax_cross_entropy", "sigmoid_cross_entropy",
                  "mean_squared_error", "absolute_difference", "log_loss",
                  "huber_loss", "cosine_distance")


@op_rule(*_SCALAR_LOSSES)
def _loss(node, ins, emit):
    if len(ins) >= 2:
        _broadcast_or_emit(ins[:2], emit, f"'{node.op}'")
    return [AVal((), _F32)]


@op_rule("sparse_softmax_cross_entropy")
def _sparse_loss(node, ins, emit):
    logits, ids = ins[0], ins[1]
    if logits.shape is not None and ids.shape is not None:
        want = logits.shape[:-1]
        if len(want) == len(ids.shape) and any(
                dims_provably_unequal(a, b)
                for a, b in zip(want, ids.shape)):
            emit("GC002", f"'sparse_softmax_cross_entropy': label ids "
                          f"{fmt_shape(ids.shape)} do not match logits "
                          f"batch dims {fmt_shape(want)}")
    return [AVal((), _F32)]


# ---------------------------------------------------------------------------
# fused attention / matmul registry ops (the optimizer's fusion targets —
# docs/OPTIMIZER.md § Fusion tier). First-class rules so the pass
# invariant checker verifies fused graphs natively (symbolic batch dims
# included) instead of through the concrete-only jax.eval_shape probe.
# ---------------------------------------------------------------------------


@op_rule("dot_product_attention")
def _dot_product_attention(node, ins, emit):
    q, k, v = ins[0], ins[1], ins[2]
    # scores promote q with k (f32 weights after softmax), the output then
    # promotes with v — k participates in the result dtype
    dt = _float_result(promote_dtypes([q.dtype, k.dtype, v.dtype]))
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.rank is not None and a.rank < 2:
            emit("GC001", f"'dot_product_attention': {name} must be rank "
                          f">= 2 ([..., L, D]), got {fmt_shape(a.shape)}")
            return [AVal(None, dt)]
    if q.shape is not None and k.shape is not None and \
            dims_provably_unequal(q.shape[-1], k.shape[-1]):
        emit("GC002", f"'dot_product_attention': q/k head dims differ — "
                      f"{q.shape[-1]} vs {k.shape[-1]} "
                      f"({fmt_shape(q.shape)} vs {fmt_shape(k.shape)})")
        return [AVal(None, dt)]
    if k.shape is not None and v.shape is not None and \
            dims_provably_unequal(k.shape[-2], v.shape[-2]):
        emit("GC002", f"'dot_product_attention': k/v sequence lengths "
                      f"differ — {k.shape[-2]} vs {v.shape[-2]} "
                      f"({fmt_shape(k.shape)} vs {fmt_shape(v.shape)})")
        return [AVal(None, dt)]
    # `causal=` needs no extra shape constraint: the generic op's
    # end-aligned tril is defined for any (Lq, Lk) pair; the flash helper's
    # t_q == t_kv restriction is a dispatch gate, not a graph invariant
    if q.shape is None or v.shape is None:
        return [AVal(None, dt)]
    if len(ins) > 3 and ins[3].shape is not None and k.shape is not None:
        m = ins[3]
        if len(m.shape) == 0:
            emit("GC001", "'dot_product_attention': mask is 0-d — expected "
                          "a key mask broadcastable over [..., Lq, Lkv]")
        elif isinstance(m.shape[-1], int) and m.shape[-1] != 1 and \
                dims_provably_unequal(m.shape[-1], k.shape[-2]):
            emit("GC002", f"'dot_product_attention': mask trailing dim "
                          f"{m.shape[-1]} matches neither 1 nor the kv "
                          f"length {k.shape[-2]}")
    return [AVal(q.shape[:-1] + (v.shape[-1],), dt)]


@op_rule("paged_decode_attention")
def _paged_decode_attention(node, ins, emit):
    q, kp, vp, pt, sl = ins[0], ins[1], ins[2], ins[3], ins[4]
    dt = q.dtype  # impl casts the f32 accumulator back to q's dtype
    want_ranks = (("q", q, 3), ("k_pages", kp, 4), ("v_pages", vp, 4),
                  ("page_table", pt, 2), ("seq_lens", sl, 1))
    for name, a, want in want_ranks:
        if a.rank is not None and a.rank != want:
            emit("GC001", f"'paged_decode_attention': {name} must be rank "
                          f"{want}, got {fmt_shape(a.shape)}")
            return [AVal(None, dt)]
    if q.shape is not None and kp.shape is not None:
        for axis_q, axis_p, what in ((1, 2, "heads"), (2, 3, "head dim")):
            if dims_provably_unequal(q.shape[axis_q], kp.shape[axis_p]):
                emit("GC002", f"'paged_decode_attention': {what} differ — "
                              f"q {fmt_shape(q.shape)} vs k_pages "
                              f"{fmt_shape(kp.shape)}")
                return [AVal(None, dt)]
    if q.shape is not None and pt.shape is not None and \
            dims_provably_unequal(q.shape[0], pt.shape[0]):
        emit("GC002", f"'paged_decode_attention': slot counts differ — "
                      f"q {fmt_shape(q.shape)} vs page_table "
                      f"{fmt_shape(pt.shape)}")
        return [AVal(None, dt)]
    if pt.dtype is not None and not np.issubdtype(pt.dtype, np.integer):
        emit("GC003", f"'paged_decode_attention': page_table dtype "
                      f"{pt.dtype} is not integral")
    return [AVal(q.shape, q.dtype)]


@op_rule("fused_matmul_bias_act")
def _fused_matmul_bias_act(node, ins, emit):
    from deeplearning4j_tpu.ops.nn_ops import FUSED_MATMUL_ACTIVATIONS

    x, w = ins[0], ins[1]
    act = node.kwargs.get("activation", "none")
    if act not in FUSED_MATMUL_ACTIVATIONS:
        emit("GC001", f"'fused_matmul_bias_act': unknown activation "
                      f"'{act}'; valid: {list(FUSED_MATMUL_ACTIVATIONS)}")
    a, b = x.shape, w.shape
    if node.kwargs.get("transpose_a"):
        a = _swap_last2(a, emit, "'fused_matmul_bias_act'")
    if node.kwargs.get("transpose_b"):
        b = _swap_last2(b, emit, "'fused_matmul_bias_act'")
    _maybe_promo_warn(ins[:2], emit)
    shape = _matmul_shape(a, b, emit, "'fused_matmul_bias_act'")
    dt = promote_dtypes([x.dtype, w.dtype])
    if len(ins) > 2 and shape is not None and ins[2].shape is not None:
        try:
            shape = broadcast_shapes([shape, ins[2].shape])
        except BroadcastError as e:
            emit("GC002", f"'fused_matmul_bias_act': bias "
                          f"{fmt_shape(ins[2].shape)} does not broadcast "
                          f"onto {fmt_shape(shape)} ({e.detail})")
            shape = None
    if len(ins) > 2:
        dt = promote_dtypes([dt, ins[2].dtype])
    if act in ("tanh", "gelu", "gelu_exact"):
        dt = _float_result(dt)  # these activations produce floats; "none"
    return [AVal(shape, dt)]    # and "relu" keep integer inputs integral


@op_rule("fused_layer_norm")
def _fused_layer_norm(node, ins, emit):
    from deeplearning4j_tpu.ops.nn_ops import FUSED_MATMUL_ACTIVATIONS

    x = ins[0]
    act = node.kwargs.get("activation", "none")
    if act not in FUSED_MATMUL_ACTIVATIONS:
        emit("GC001", f"'fused_layer_norm': unknown activation '{act}'; "
                      f"valid: {list(FUSED_MATMUL_ACTIVATIONS)}")
    axis = node.kwargs.get("axis", -1)
    if x.rank is not None and axis not in (-1, x.rank - 1):
        emit("GC001", f"'fused_layer_norm': trailing-axis only (the impl "
                      f"raises for axis={axis} at rank {x.rank}); use the "
                      f"catalog layer_norm for other axes")
    for what, a in [("gain", ins[1])] + \
            ([("bias", ins[2])] if len(ins) > 2 else []):
        if a.rank is not None and a.rank != 1:
            emit("GC001", f"'fused_layer_norm': {what} must be rank 1, "
                          f"got {fmt_shape(a.shape)}")
        elif x.shape is not None and a.shape is not None and \
                dims_provably_unequal(a.shape[0], x.shape[-1]):
            emit("GC002", f"'fused_layer_norm': {what} "
                          f"{fmt_shape(a.shape)} does not match the "
                          f"normalized dim of x {fmt_shape(x.shape)}")
    return [AVal(x.shape, _float_result(x.dtype))]


@op_rule("fused_updater_step")
def _fused_updater_step(node, ins, emit):
    # (param, grad, lr, step, *state) -> (new_param, *new_state): every
    # array leaf keeps the param's shape/dtype; lr/step are traced scalars
    p = ins[0]
    state = ins[4:]
    kind = node.kwargs.get("kind", "Sgd")
    from deeplearning4j_tpu.nn.updater import UPDATERS

    if kind not in UPDATERS:
        emit("GC001", f"'fused_updater_step': unknown updater kind "
                      f"'{kind}'; valid: {sorted(UPDATERS)}")
    else:
        from deeplearning4j_tpu.ops.pallas_updater import _updater_and_keys

        try:
            _, keys = _updater_and_keys(
                kind, tuple(sorted((k, v) for k, v in node.kwargs.items()
                                   if k != "kind")))
        except (ValueError, TypeError):
            keys = None  # bad hyperparams: the impl raises its own error
        if keys is not None and len(state) != len(keys):
            emit("GC001", f"'fused_updater_step[{kind}]': expected "
                          f"{len(keys)} state arrays {list(keys)}, got "
                          f"{len(state)} — the trace will raise")
    for what, a in [("grad", ins[1])] + \
            [(f"state[{i}]", s) for i, s in enumerate(state)]:
        if p.shape is None or a.shape is None:
            continue
        # rank first — zip would silently truncate a rank mismatch
        if len(a.shape) != len(p.shape) or any(
                dims_provably_unequal(d1, d2)
                for d1, d2 in zip(p.shape, a.shape)):
            emit("GC002", f"'fused_updater_step': {what} "
                          f"{fmt_shape(a.shape)} does not match param "
                          f"{fmt_shape(p.shape)}")
    for what, a in (("lr", ins[2]), ("step", ins[3])):
        if a.rank is not None and a.rank != 0:
            emit("GC001", f"'fused_updater_step': {what} must be a scalar, "
                          f"got {fmt_shape(a.shape)}")
    return [AVal(p.shape, p.dtype)] + \
        [AVal(s.shape if s.shape is not None else p.shape,
              s.dtype if s.dtype is not None else p.dtype) for s in state]


@op_rule("quantize_int8")
def _quantize_int8(node, ins, emit):
    x = ins[0]
    axis = node.kwargs.get("axis")
    if axis is None:
        scale_shape: Optional[Shape] = ()
    elif x.shape is not None:
        # the impl accepts an int or a tuple of axes (jnp.max semantics)
        axes = axis if isinstance(axis, (tuple, list)) else (axis,)
        normed = [_norm_axis(int(a), len(x.shape)) for a in axes]
        if any(a is None for a in normed):
            emit("GC001", f"'quantize_int8': axis {axis} out of range for "
                          f"{fmt_shape(x.shape)}")
            scale_shape = None
        else:
            keep = set(normed)
            scale_shape = tuple(1 if i in keep else d
                                for i, d in enumerate(x.shape))
    else:
        scale_shape = None
    return [AVal(x.shape, np.dtype(np.int8)), AVal(scale_shape, _F32)]


@op_rule("dequantize_int8")
def _dequantize_int8(node, ins, emit):
    q, scale = ins[0], ins[1]
    shape = q.shape
    if q.shape is not None and scale.shape is not None:
        try:
            shape = broadcast_shapes([q.shape, scale.shape])
        except BroadcastError as e:
            emit("GC002", f"'dequantize_int8': scale "
                          f"{fmt_shape(scale.shape)} does not broadcast "
                          f"onto q {fmt_shape(q.shape)} ({e.detail})")
            shape = None
    return [AVal(shape, _F32)]


@op_rule("matmul_int8")
def _matmul_int8(node, ins, emit):
    x, wq = ins[0], ins[1]
    if wq.dtype is not None and wq.dtype != np.dtype(np.int8):
        emit("GC003", f"'matmul_int8': weights must be int8, got {wq.dtype}")
    if len(ins) > 2 and ins[2].rank is not None:
        ws = ins[2]
        # (N,) or the keepdims (1, N) that quantize_int8(axis=0) emits —
        # the impl reshapes to (1, N) either way
        ok = ws.rank == 1 or (
            ws.rank == 2 and ws.shape is not None
            and not dims_provably_unequal(ws.shape[0], 1))
        if not ok:
            emit("GC001", f"'matmul_int8': w_scale must be (N,) or (1, N), "
                          f"got {fmt_shape(ws.shape)}")
    shape = _matmul_shape(x.shape, wq.shape, emit, "'matmul_int8'")
    return [AVal(shape, x.dtype)]  # de-scale casts back to x's dtype


# ---------------------------------------------------------------------------
# conv / pool (NHWC, matching ops/nn_ops.py)
# ---------------------------------------------------------------------------


def _pair(v):
    return (tuple(int(a) for a in v) if isinstance(v, (tuple, list))
            else (int(v), int(v)))


def _conv_dim(n: DimEntry, k: int, s: int, d: int, same: bool) -> DimEntry:
    if not isinstance(n, int):
        return n if s == 1 and (same or k == 1) else None
    if same:
        return -(-n // s)  # ceil
    eff = (k - 1) * d + 1
    return max(0, (n - eff) // s + 1)


@op_rule("conv2d")
def _conv2d(node, ins, emit):
    x, w = ins[0], ins[1]
    for a, what, want in ((x, "input", 4), (w, "kernel", 4)):
        if a.rank is not None and a.rank != want:
            emit("GC001", f"'conv2d': {what} must be rank {want} "
                          f"(NHWC/HWIO), got {fmt_shape(a.shape)}")
            return [AVal(None, _float_result(x.dtype))]
    if x.shape is None or w.shape is None:
        return [AVal(None, _float_result(x.dtype))]
    groups = int(node.kwargs.get("feature_group_count", 1))
    cin, win = x.shape[3], w.shape[2]
    if isinstance(cin, int) and isinstance(win, int) and cin != win * groups:
        emit("GC002", f"'conv2d': input channels {cin} != kernel input "
                      f"channels {win} x groups {groups} "
                      f"({fmt_shape(x.shape)} * {fmt_shape(w.shape)})")
        return [AVal(None, _float_result(x.dtype))]
    s = _pair(node.kwargs.get("stride", 1))
    d = _pair(node.kwargs.get("dilation", 1))
    padding = node.kwargs.get("padding", "same")
    same = isinstance(padding, str) and padding.upper() == "SAME"
    if not isinstance(padding, str):
        return [AVal((x.shape[0], None, None, w.shape[3]),
                     _float_result(x.dtype))]
    kh, kw = w.shape[0], w.shape[1]
    h = _conv_dim(x.shape[1], kh, s[0], d[0], same) \
        if isinstance(kh, int) else None
    ww = _conv_dim(x.shape[2], kw, s[1], d[1], same) \
        if isinstance(kw, int) else None
    return [AVal((x.shape[0], h, ww, w.shape[3]), _float_result(x.dtype))]


@op_rule("maxpool2d", "avgpool2d", "pnormpool2d")
def _pool2d(node, ins, emit):
    x = ins[0]
    if x.rank is not None and x.rank != 4:
        emit("GC001", f"'{node.op}': input must be rank 4 (NHWC), got "
                      f"{fmt_shape(x.shape)}")
        return [AVal(None, x.dtype)]
    if x.shape is None:
        return [AVal(None, x.dtype)]
    kernel = _pair(node.kwargs.get("kernel", 1))
    stride = node.kwargs.get("stride")
    s = _pair(stride if stride is not None else kernel)
    padding = node.kwargs.get("padding", "valid")
    same = isinstance(padding, str) and padding.upper() == "SAME"
    if not isinstance(padding, str):
        return [AVal((x.shape[0], None, None, x.shape[3]), x.dtype)]
    h = _conv_dim(x.shape[1], kernel[0], s[0], 1, same)
    w = _conv_dim(x.shape[2], kernel[1], s[1], 1, same)
    return [AVal((x.shape[0], h, w, x.shape[3]), x.dtype)]


@op_rule("upsampling2d")
def _upsampling2d(node, ins, emit):
    x = ins[0]
    if x.rank is not None and x.rank != 4:
        emit("GC001", f"'upsampling2d': input must be rank 4 (NHWC), got "
                      f"{fmt_shape(x.shape)}")
        return [AVal(None, x.dtype)]
    if x.shape is None:
        return [AVal(None, x.dtype)]
    sh, sw = _pair(node.kwargs.get("size", 2))
    h = x.shape[1] * sh if isinstance(x.shape[1], int) else None
    w = x.shape[2] * sw if isinstance(x.shape[2], int) else None
    return [AVal((x.shape[0], h, w, x.shape[3]), x.dtype)]


@op_rule("global_avg_pool", "global_max_pool")
def _global_pool(node, ins, emit):
    x = ins[0]
    if x.shape is None:
        return [AVal(None, x.dtype)]
    if len(x.shape) != 4:
        emit("GC001", f"'{node.op}': input must be rank 4 (NHWC), got "
                      f"{fmt_shape(x.shape)}")
        return [AVal(None, x.dtype)]
    return [AVal((x.shape[0], x.shape[3]), x.dtype)]
