"""graftcheck abstract domain — symbolic shapes, dtypes, const values.

The abstract value of one graph tensor is an :class:`AVal`:

* ``shape`` — ``None`` (rank unknown) or a tuple whose entries are a
  non-negative ``int`` (concrete), a :class:`Dim` (named symbolic dim —
  every ``None``/``-1`` placeholder axis gets one, so ``(None, 128)``
  batches flow through matmuls and residual adds without losing the
  "these two batch dims are THE SAME dim" fact), or ``None`` (unknown).
* ``dtype`` — a ``np.dtype`` or ``None`` (unknown).
* ``value`` — a small concrete ``np.ndarray`` when the tensor is
  statically known (CONSTANT variables and the numpy-static
  ``shape_of``/``stack``/``unstack`` chains) — the interpreter's constant
  environment, used by rules that branch on values (reshape targets,
  concat of shape pieces).

The lattice is the usual "more ``None`` = less information"; every rule
must be *sound*: emit an error finding only when the mismatch is provable
from concrete entries, degrade to unknown otherwise.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

# largest element count a const value is carried for — shape chains are
# tiny; big constants only need shape/dtype
CONST_VALUE_LIMIT = 4096

DimEntry = Union[int, "Dim", None]
Shape = Optional[Tuple[DimEntry, ...]]


class Dim:
    """A named symbolic dimension (batch/sequence axes declared None/-1)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other) -> bool:
        return isinstance(other, Dim) and other.name == self.name

    def __ne__(self, other) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        return hash(("Dim", self.name))


class AVal:
    """Abstract tensor value: symbolic shape + dtype + optional constant."""

    __slots__ = ("shape", "dtype", "value")

    def __init__(self, shape: Shape = None, dtype=None,
                 value: Optional[np.ndarray] = None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.value = value

    # ------------------------------------------------------------- factories
    @staticmethod
    def unknown() -> "AVal":
        return AVal()

    @staticmethod
    def of_array(arr, keep_value: bool = False) -> "AVal":
        # read shape/dtype without np.asarray — a device array (BERT-scale
        # weights) must not pay a host copy just to be abstracted
        shape = tuple(int(d) for d in np.shape(arr))
        dtype = getattr(arr, "dtype", None)
        if dtype is None:
            dtype = np.asarray(arr).dtype
        value = None
        if keep_value:
            n = 1
            for d in shape:
                n *= d
            if n <= CONST_VALUE_LIMIT:
                value = np.asarray(arr)
        return AVal(shape=shape, dtype=dtype, value=value)

    @staticmethod
    def of_placeholder(name: str, shape, dtype) -> "AVal":
        """Declared placeholder metadata → symbolic aval. ``None``/``-1``
        axes become named Dims so identical symbols unify downstream."""
        if shape is None:
            return AVal(dtype=dtype)
        sym = tuple(Dim(f"{name}.{i}") if d is None or int(d) < 0 else int(d)
                    for i, d in enumerate(shape))
        return AVal(shape=sym, dtype=dtype)

    # -------------------------------------------------------------- queries
    @property
    def rank(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)

    def is_concrete(self) -> bool:
        """Fully concrete shape (every entry an int)."""
        return self.shape is not None and all(
            isinstance(d, int) for d in self.shape)

    def concrete_shape(self) -> Optional[Tuple[int, ...]]:
        return tuple(self.shape) if self.is_concrete() else None  # type: ignore[arg-type]

    def num_elements(self) -> Optional[int]:
        s = self.concrete_shape()
        if s is None:
            return None
        n = 1
        for d in s:
            n *= d
        return n

    def __repr__(self) -> str:
        return f"AVal(shape={fmt_shape(self.shape)}, dtype={self.dtype})"


def fmt_shape(shape: Shape) -> str:
    if shape is None:
        return "?"
    return "(" + ", ".join("?" if d is None else str(d) for d in shape) + ")"


def dims_provably_unequal(a: DimEntry, b: DimEntry) -> bool:
    """True only when both entries are concrete ints and differ — the sound
    precondition for every error-severity shape finding."""
    return isinstance(a, int) and isinstance(b, int) and a != b


def dims_equal(a: DimEntry, b: DimEntry) -> bool:
    """Known-equal: same int, or same symbolic Dim."""
    if isinstance(a, int) and isinstance(b, int):
        return a == b
    if isinstance(a, Dim) and isinstance(b, Dim):
        return a == b
    return False
