from deeplearning4j_tpu.analysis.cli import main

main()
