"""graftcheck abstract interpreter — walk a SameDiff recording once,
propagating symbolic shapes/dtypes (and a constant env) through the per-op
rules, emitting GC-coded findings with node provenance.

Resolution order per node:

1. instance-local ops (control-flow closures) — deliberately opaque:
   outputs unknown, no finding;
2. a handwritten rule from ``rules.py`` (handles symbolic dims);
3. a ``jax.eval_shape`` probe of the real impl when every input is
   concrete (the registry's "shape functions for free" — exact JAX
   shape/dtype semantics at trace cost, zero FLOPs); host-static impls
   (numpy ``shape_of``/``stack`` chains) abort the probe harmlessly;
4. the sound unknown fallback + GC006.

Constant env: CONSTANT variables seed concrete values; a whitelisted set
of ops re-executes for real (tiny arrays only) so numpy-static
``shape_of → stack → reshape`` chains stay concrete through the check,
exactly as they do at trace time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.analysis.report import CheckReport, make_finding
from deeplearning4j_tpu.analysis.rules import RULES
from deeplearning4j_tpu.analysis.values import AVal, CONST_VALUE_LIMIT
from deeplearning4j_tpu.lint.core import Finding

# ops re-executed on concrete inputs to keep the constant env flowing —
# the numpy-static shape-chain surface plus the integer arithmetic that
# glues it together. Everything here is cheap on <=CONST_VALUE_LIMIT
# element arrays.
_CONST_EVAL_OPS = frozenset([
    "shape_of", "stack", "unstack", "unstack_first", "size", "cast",
    "concat", "squeeze", "expand_dims", "reshape", "transpose", "permute",
    "gather", "slice", "strided_slice", "identity",
    "add", "sub", "mul", "div", "floormod", "maximum", "minimum", "neg",
])

_EVAL_SHAPE_CACHE: Dict[Any, Optional[List[AVal]]] = {}
_EVAL_SHAPE_CACHE_MAX = 2048


def _resolve_impl(op: str, local_ops) -> Optional[Callable[..., Any]]:
    from deeplearning4j_tpu.autodiff.samediff import resolve_graph_op

    try:
        return resolve_graph_op(op, local_ops)
    except KeyError:
        return None


def _canon_for_cache(kwargs: Dict[str, Any]):
    # the optimizer's hardened canonicalizer: ndarray -> tobytes (str(v)
    # would summarize large arrays with '...' and collide cache keys),
    # repr-sorted dict keys, None on anything un-canonicalizable
    from deeplearning4j_tpu.autodiff.optimize import _canon_kwargs

    return _canon_kwargs(kwargs)


def _eval_shape_probe(op: str, fn, ins: Sequence[AVal],
                      kwargs: Dict[str, Any]
                      ) -> Tuple[Optional[List[AVal]], Optional[str]]:
    """(avals, None) on success; (None, reason) otherwise. reason None
    means "host-static impl, silently unknown"."""
    if not ins or any(not a.is_concrete() or a.dtype is None for a in ins):
        return None, "inputs have symbolic/unknown shape or dtype"
    import jax

    ck = _canon_for_cache(kwargs)
    cache_key = None
    if ck is not None:
        # the RESOLVED impl is part of the key: re-registering an op under
        # the same name (tests monkeypatching GRAPH_OPS) must not serve
        # the old impl's cached avals (fn itself, not id(fn) — ids recycle
        # after GC; the bounded cache holding a ref is fine)
        cache_key = (op, fn,
                     tuple((a.concrete_shape(), a.dtype) for a in ins), ck)
        cached = _EVAL_SHAPE_CACHE.get(cache_key)
        if cached is not None:
            return list(cached), None
    args = [jax.ShapeDtypeStruct(a.concrete_shape(), a.dtype) for a in ins]
    result: Optional[List[AVal]] = None
    reason: Optional[str] = None
    try:
        # close over kwargs so axis/k/… stay static Python values —
        # eval_shape would otherwise abstract them into tracers
        out = jax.eval_shape(lambda *a: fn(*a, **kwargs), *args)
        result = [AVal(tuple(int(d) for d in leaf.shape), leaf.dtype)
                  for leaf in jax.tree_util.tree_leaves(out)]
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        reason = None  # host-static impl: unknowable statically, not a bug
    except Exception as exc:  # noqa: BLE001 — probe must never kill the check
        reason = f"{type(exc).__name__}: {exc}"
    if result is not None:
        if cache_key is not None and \
                len(_EVAL_SHAPE_CACHE) < _EVAL_SHAPE_CACHE_MAX:
            _EVAL_SHAPE_CACHE[cache_key] = result
        return result, None
    return None, "" if reason is None else reason


def _const_eval(op: str, fn, node, ins: Sequence[AVal]
                ) -> Optional[List[AVal]]:
    """Execute the real impl on fully known small inputs (constant env)."""
    if op not in _CONST_EVAL_OPS or fn is None:
        return None
    if op == "shape_of" and ins and ins[0].is_concrete():
        # the value depends only on the input SHAPE — concrete even when
        # the input tensor itself is not (matches the numpy impl)
        s = ins[0].concrete_shape()
        dt = np.int64 if max(s, default=0) > 2**31 else np.int32
        return [AVal.of_array(np.asarray(s, dt), keep_value=True)]
    if op == "size" and ins and ins[0].is_concrete():
        return [AVal.of_array(np.asarray(ins[0].num_elements(), np.int32),
                              keep_value=True)]
    if any(a.value is None for a in ins):
        return None
    try:
        import jax.numpy as jnp

        # execute under JAX semantics, not numpy's: np.int32/np.int32
        # promotes to float64 on host but float32 under jax x32 — the
        # values must match what the fold pass (which runs on the jnp
        # constant env) actually produces, or the invariance checker
        # reports a phantom dtype change
        res = fn(*[jnp.asarray(a.value) for a in ins], **node.kwargs)
    except Exception:
        return None  # the rule already reported what it could prove
    vals = [res] if len(node.outputs) == 1 else list(res)
    if len(vals) != len(node.outputs):
        return None
    out = []
    for v in vals:
        a = np.asarray(v)
        if a.size > CONST_VALUE_LIMIT:
            out.append(AVal.of_array(a))
        else:
            out.append(AVal.of_array(a, keep_value=True))
    return out


def infer_nodes(indexed_nodes: Sequence[Tuple[int, Any]],
                avals: Dict[str, AVal],
                local_ops: Optional[Dict[str, Callable]] = None,
                graph_name: str = "<samediff>",
                findings: Optional[List[Finding]] = None,
                known_names: Optional[set] = None) -> Dict[str, AVal]:
    """Propagate avals through ``indexed_nodes`` [(node_index, node), ...]
    in order, mutating and returning ``avals``. ``known_names``: every
    name legally consumable before the walk (vars with values,
    placeholders, plan constants); defaults to ``avals``' keys. Findings
    (if a list is passed) collect GC-coded results."""
    local_ops = local_ops or {}
    sink: List[Finding] = findings if findings is not None else []
    defined = set(known_names if known_names is not None else avals)

    for idx, node in indexed_nodes:
        out_name = node.outputs[0] if node.outputs else "?"

        def emit(code: str, message: str, _idx=idx, _node=node,
                 _out=out_name):
            sink.append(make_finding(
                graph_name, _idx, code,
                f"node '{_out}' (op {_node.op}): {message}"))

        ins: List[AVal] = []
        dangling = False
        for name in node.inputs:
            if name not in defined:
                emit("GC004", f"consumes '{name}', which no variable or "
                              f"earlier node defines (dangling input / "
                              f"graph out of order)")
                dangling = True
                ins.append(AVal.unknown())
            else:
                ins.append(avals.get(name) or AVal.unknown())

        outs: Optional[List[AVal]] = None
        fn = _resolve_impl(node.op, local_ops)
        if node.op in local_ops:
            outs = [AVal.unknown() for _ in node.outputs]
        elif dangling:
            outs = [AVal.unknown() for _ in node.outputs]
        elif node.op in RULES:
            outs = RULES[node.op](node, ins, emit)
        elif fn is None:
            emit("GC006", "op is not resolvable in GRAPH_OPS or the "
                          "declarable-op registry; outputs are opaque")
        else:
            probed, reason = _eval_shape_probe(node.op, fn, ins, node.kwargs)
            if probed is not None:
                outs = probed
            elif reason:  # empty string = host-static, stay silent
                emit("GC006", f"no inference rule and the eval_shape probe "
                              f"could not run ({reason}); outputs are "
                              f"opaque to the checker")

        # constant env: real execution on known small inputs wins
        concrete = _const_eval(node.op, fn, node, ins)
        if concrete is not None:
            outs = concrete

        if outs is None:
            outs = [AVal.unknown() for _ in node.outputs]
        if len(outs) < len(node.outputs):
            outs = list(outs) + [AVal.unknown()
                                 for _ in range(len(node.outputs) - len(outs))]
        for name, aval in zip(node.outputs, outs):
            avals[name] = aval
            defined.add(name)
    return avals


# ---------------------------------------------------------------------------
# SameDiff entry points
# ---------------------------------------------------------------------------


def seed_avals(sd) -> Tuple[Dict[str, AVal], set]:
    """(avals, known-names) for a SameDiff instance: bound arrays
    (VARIABLE/CONSTANT) give exact avals — constants keep their value for
    the const env — and PLACEHOLDER declarations give symbolic avals
    (None/-1 axes become named Dims)."""
    avals: Dict[str, AVal] = {}
    known: set = set()
    for name, v in sd._vars.items():
        if name in sd._arrays:
            keep = v.vtype == "CONSTANT"
            avals[name] = AVal.of_array(sd._arrays[name], keep_value=keep)
            known.add(name)
        elif v.vtype == "PLACEHOLDER":
            avals[name] = AVal.of_placeholder(name, v.shape, v.dtype)
            known.add(name)
    return avals, known


def check_samediff(sd, outputs: Optional[Sequence[str]] = None,
                   graph_name: str = "<samediff>") -> CheckReport:
    """Verify a SameDiff graph statically. ``outputs=None`` checks every
    recorded node; with explicit outputs only their ancestor subgraph is
    walked (what a trace of those outputs would execute)."""
    findings: List[Finding] = []
    avals, known = seed_avals(sd)

    indexed = list(enumerate(sd._nodes))
    if outputs is not None:
        wanted = set(outputs)
        keep: List[Tuple[int, Any]] = []
        for idx, node in reversed(indexed):
            if any(o in wanted for o in node.outputs):
                keep.append((idx, node))
                wanted.update(node.inputs)
        keep.reverse()
        indexed = keep

    infer_nodes(indexed, avals, sd._local_ops, graph_name, findings, known)

    # interface sanity: requested / recorded graph outputs must exist
    for out in (outputs if outputs is not None else sd.graph_outputs):
        if out not in sd._vars:
            findings.append(make_finding(
                graph_name, len(sd._nodes), "GC004",
                f"graph output '{out}' names no variable in the graph"))
    return CheckReport(graph_name, findings, avals)
