"""graftcheck CLI — the gate's ``check`` stage.

    python -m deeplearning4j_tpu.analysis [options]
    python tools/graftcheck.py              # identical thin wrapper

Runs the abstract shape/dtype interpreter over the fixture zoo
(``analysis/fixtures.py``: the examples' SameDiff graphs, symbolic-batch
CNN/BERT encoders, a numpy-static shape chain, an ONNX-dialect import,
and zoo networks) and diffs the findings against the committed
shrink-only ``check_baseline.json``.

Options:
    --baseline PATH    baseline file (default: <repo>/check_baseline.json)
    --write-baseline   regenerate the baseline (shrink-only; new findings
                       are REFUSED and exit 1 — see --allow-growth)
    --allow-growth     allow --write-baseline to add new keys (onboarding)
    --json             emit exactly ONE machine-readable JSON summary line
                       (the tools/gate.py driver-artifact contract)
    --list-codes       print the GC code catalog and exit

Exit code 0 iff there are no findings beyond the grandfathered baseline.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from deeplearning4j_tpu.lint.core import Finding, run_baselined_cli

_CHECK_BASELINE_COMMENT = (
    "graftcheck grandfathered findings — every entry is debt; shrink, "
    "never grow. Regenerate: make check-baseline")


def find_repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_findings() -> List[Finding]:
    """Check every clean fixture; any finding at all is reportable (the
    committed baseline is empty — the fixtures must stay clean)."""
    from deeplearning4j_tpu.analysis import check_network, check_samediff
    from deeplearning4j_tpu.analysis import fixtures
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    findings: List[Finding] = []
    for name, graph in fixtures.clean_fixtures():
        if isinstance(graph, SameDiff):
            report = check_samediff(graph, graph_name=name)
        else:
            report = check_network(graph, graph_name=name)
        findings.extend(report.findings)
    return sorted(findings)


def run(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="graftcheck", description=__doc__)
    ap.add_argument("--baseline", default=None)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--allow-growth", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--list-codes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_codes:
        from deeplearning4j_tpu.analysis.report import GC_CODES
        for code, (severity, title) in sorted(GC_CODES.items()):
            print(f"{code}  {severity:7s}  {title}")
        return 0

    # pin the CPU backend before any fixture touches the registries so the
    # check stage can never hang on an unreachable TPU (the GL002 class)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass

    baseline_path = args.baseline or os.path.join(find_repo_root(),
                                                  "check_baseline.json")
    findings = collect_findings()

    # shared baseline-CLI tail (lint/core.py — also drives graftlint)
    return run_baselined_cli(
        "graftcheck", findings, baseline_path,
        write=args.write_baseline, allow_growth=args.allow_growth,
        json_mode=args.json, comment=_CHECK_BASELINE_COMMENT,
        fail_hint="an op rule, importer, or fixture regressed; see "
                  "docs/ANALYSIS.md")


def main() -> None:
    sys.exit(run())
