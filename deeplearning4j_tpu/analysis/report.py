"""graftcheck finding model — GC error codes, reports, raise policy.

Findings reuse :class:`deeplearning4j_tpu.lint.core.Finding` (path, line,
rule, severity, message) so the graftlint baseline machinery
(``load_baseline``/``write_baseline``/``diff_baseline``) works unchanged
against ``check_baseline.json``. For a graph finding:

* ``path``  — the logical graph name (``onnx:bert_base``, ``zoo/mlp`` …),
  stable across runs so baseline keys survive;
* ``line``  — the 1-based node position in the recording (provenance for
  "which node", not a source line);
* ``message`` — leads with the node provenance: op name + the node's
  output name, which for imported graphs IS the source-graph node name
  (importers rename outputs to source names — imports/ir.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.lint.core import Finding

# code -> (severity, one-line title). Severity contract: errors are
# PROVABLE miscompiles/misimports (a trace would fail or silently compute
# the wrong thing); warnings are opacity/precision hazards.
GC_CODES: Dict[str, Tuple[str, str]] = {
    "GC001": ("error", "rank mismatch / invalid axis"),
    "GC002": ("error", "broadcast or contraction failure"),
    "GC003": ("warning", "dtype promotion surprise"),
    "GC004": ("error", "unbound placeholder / dangling input"),
    "GC005": ("error", "reshape element-count mismatch"),
    "GC006": ("warning", "unknown-op opacity"),
}


class GraphCheckError(ValueError):
    """Raised when a checked graph carries error-severity findings."""

    def __init__(self, findings: List[Finding]):
        self.findings = list(findings)
        lines = [f.render() for f in self.findings[:20]]
        extra = len(self.findings) - len(lines)
        if extra > 0:
            lines.append(f"... and {extra} more")
        super().__init__(
            "graftcheck: graph failed static shape/dtype verification "
            f"({len(self.findings)} error finding"
            f"{'s' if len(self.findings) != 1 else ''}):\n"
            + "\n".join(lines))


class PassInvariantError(RuntimeError):
    """An optimizer pass changed an interface shape/dtype it must preserve
    (autodiff/optimize.py runs the interpreter between passes)."""

    def __init__(self, pass_name: str, output: str, kind: str,
                 before, after):
        self.pass_name = pass_name
        self.output = output
        super().__init__(
            f"optimizer pass '{pass_name}' changed the {kind} of graph "
            f"output '{output}': {before} -> {after} — the pass pipeline "
            f"must be shape/dtype-preserving; disable it via "
            f"SameDiff(optimize_passes=...) and report the miscompile")


class CheckReport:
    """Result of one graph check: findings + the inferred abstract values
    (name -> AVal) for introspection/tests."""

    def __init__(self, graph_name: str, findings: List[Finding],
                 avals: Optional[Dict[str, object]] = None):
        self.graph_name = graph_name
        self.findings = sorted(findings)
        self.avals = avals or {}

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_on_errors(self) -> "CheckReport":
        if self.errors:
            raise GraphCheckError(self.errors)
        return self

    def render(self) -> str:
        if not self.findings:
            return f"graftcheck: {self.graph_name}: clean"
        return "\n".join(f.render() for f in self.findings)

    def __repr__(self) -> str:
        return (f"CheckReport({self.graph_name!r}, "
                f"{len(self.errors)} errors, {len(self.warnings)} warnings)")


def make_finding(graph_name: str, node_index: int, code: str,
                 message: str) -> Finding:
    severity, _title = GC_CODES[code]
    return Finding(path=graph_name, line=node_index + 1, rule=code,
                   severity=severity, message=message)
