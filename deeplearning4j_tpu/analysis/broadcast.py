"""Symbolic NumPy-style broadcasting and jax dtype promotion.

Soundness contract (shared with every rule in ``rules.py``): a broadcast
*error* is reported only when two aligned entries are both concrete ints,
neither is 1, and they differ. Symbolic/unknown entries degrade the result
dim, never produce an error — a ``(None, 128)`` batch against a concrete
``(4, 128)`` activation must check clean.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.analysis.values import (
    Dim, DimEntry, Shape, fmt_shape)


class BroadcastError(Exception):
    """Provable broadcast failure; ``.detail`` names the offending axis."""

    def __init__(self, detail: str):
        super().__init__(detail)
        self.detail = detail


def broadcast_dim(a: DimEntry, b: DimEntry) -> DimEntry:
    """One aligned axis pair → result entry (raises on provable failure)."""
    if isinstance(a, int) and isinstance(b, int):
        if a == b:
            return a
        if a == 1:
            return b
        if b == 1:
            return a
        raise BroadcastError(f"{a} vs {b}")
    if a is None or b is None:
        # unknown vs concrete>1 → the concrete dim (any valid execution
        # yields it); unknown vs 1 or unknown vs symbol → unknown
        other = a if b is None else b
        if isinstance(other, int) and other > 1:
            return other
        return None
    # at least one symbolic Dim
    if isinstance(a, Dim) and isinstance(b, Dim):
        return a if a == b else None
    sym, conc = (a, b) if isinstance(a, Dim) else (b, a)
    if isinstance(conc, int):
        if conc == 1:
            return sym
        return conc  # symbol must equal the concrete dim in a valid run
    return None


def broadcast_shapes(shapes: Sequence[Shape]) -> Shape:
    """NumPy-style broadcast of N symbolic shapes (right-aligned).

    Raises :class:`BroadcastError` only on a provable mismatch; any shape
    with unknown rank makes the whole result unknown."""
    known = [s for s in shapes if s is not None]
    if len(known) != len(shapes) or not known:
        return None
    rank = max(len(s) for s in known)
    out: List[DimEntry] = []
    for axis in range(rank):
        entry: DimEntry = 1
        for s in known:
            idx = len(s) - rank + axis
            d = s[idx] if idx >= 0 else 1
            try:
                entry = broadcast_dim(entry, d)
            except BroadcastError:
                raise BroadcastError(
                    f"axis {axis - rank}: "
                    + " vs ".join(fmt_shape(s) for s in known))
        out.append(entry)
    return tuple(out)


def promote_dtypes(dtypes: Sequence[Optional[np.dtype]]) -> Optional[np.dtype]:
    """jax promotion lattice over known dtypes; None if any is unknown."""
    if any(dt is None for dt in dtypes) or not dtypes:
        return None
    import jax.numpy as jnp

    out = dtypes[0]
    for dt in dtypes[1:]:
        out = np.dtype(jnp.promote_types(out, dt))
    return out


def is_float_dtype(dt: Optional[np.dtype]) -> bool:
    """Floating-point including the ml_dtypes extended types (bfloat16,
    float8_*) that numpy classifies as kind 'V', not inexact."""
    return dt is not None and (np.issubdtype(dt, np.inexact)
                               or dt.name.startswith(("bfloat", "float8")))


def promotion_surprise(dtypes: Sequence[Optional[np.dtype]]
                       ) -> Optional[str]:
    """The GC003 predicate: mixed float widths (bf16+f32, f32+f64 — the
    silent up/downcast class the optimizer's strip guard exists for), or a
    promotion to a dtype wider than every input (int32+uint32→int64).
    Returns a human-readable reason, or None when unsurprising."""
    known = [dt for dt in dtypes if dt is not None]
    if len(known) < 2:
        return None
    inexact = [dt for dt in known if is_float_dtype(dt)]
    if len(inexact) >= 2 and len(set(inexact)) > 1:
        names = sorted({dt.name for dt in inexact})
        return f"mixed float widths {' vs '.join(names)}"
    promoted = promote_dtypes(known)
    if promoted is not None and all(promoted != dt for dt in known):
        names = " + ".join(dt.name for dt in known)
        return f"{names} promotes to {promoted.name} (wider than every input)"
    return None
