"""WordPiece tokenization + BERT data iterator.

Reference parity:
  * deeplearning4j-nlp: text/tokenization/tokenizer/BertWordPieceTokenizer
    (greedy longest-match-first wordpiece over a vocab file) and
    iterator/BertIterator.java (sentence → ids with [CLS]/[SEP], padding,
    masking; tasks: SEQ_CLASSIFICATION and UNSUPERVISED MLM with 15%
    masking, 80/10/10 mask/random/keep).

Host-side numpy; the device only ever sees int32 id/mask batches.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIALS = [PAD, UNK, CLS, SEP, MASK]


def build_vocab(corpus: Iterable[str], max_size: int = 30000,
                min_count: int = 1) -> Dict[str, int]:
    """Build a word-level + char-fallback wordpiece vocab from a corpus
    (the role of the reference's pretrained vocab file, offline)."""
    from collections import Counter

    words: Counter = Counter()
    chars: Counter = Counter()
    for line in corpus:
        for w in line.lower().split():
            words[w] += 1
            for ch in w:
                chars[ch] += 1
    vocab: Dict[str, int] = {}
    for sp in SPECIALS:
        vocab[sp] = len(vocab)
    for ch, c in chars.most_common():
        if len(vocab) >= max_size:
            break
        vocab.setdefault(ch, len(vocab))
        vocab.setdefault("##" + ch, len(vocab))
    for w, c in words.most_common():
        if c < min_count or len(vocab) >= max_size:
            continue
        vocab.setdefault(w, len(vocab))
    return vocab


class BertWordPieceTokenizer:
    """Greedy longest-match-first wordpiece (reference
    BertWordPieceTokenizer / the standard BERT algorithm)."""

    def __init__(self, vocab: Dict[str, int], lower_case: bool = True,
                 max_chars_per_word: int = 100):
        self.vocab = vocab
        self.lower_case = lower_case
        self.max_chars = max_chars_per_word
        self.inv = {i: t for t, i in vocab.items()}

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        if self.lower_case:
            text = text.lower()
        for word in text.split():
            if len(word) > self.max_chars:
                out.append(UNK)
                continue
            start = 0
            pieces: List[str] = []
            bad = False
            while start < len(word):
                end = len(word)
                cur = None
                while start < end:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    if sub in self.vocab:
                        cur = sub
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                pieces.append(cur)
                start = end
            out.extend([UNK] if bad else pieces)
        return out

    def encode(self, text: str) -> List[int]:
        return [self.vocab.get(t, self.vocab[UNK]) for t in self.tokenize(text)]

    def decode(self, ids: Sequence[int]) -> str:
        toks = [self.inv.get(int(i), UNK) for i in ids]
        s = " ".join(toks).replace(" ##", "")
        return s


class BertIterator:
    """BertIterator.java analog.

    task='seq_classification': yields (token_ids, segment_ids, input_mask,
    one-hot labels). task='unsupervised' (MLM): yields (masked_ids,
    segment_ids, input_mask, mlm_labels, mlm_mask) with 15% selection,
    80/10/10 mask/random/keep — the reference's UNSUPERVISED task.
    """

    def __init__(self, tokenizer: BertWordPieceTokenizer,
                 sentences: Sequence[str],
                 labels: Optional[Sequence[int]] = None,
                 num_classes: int = 2,
                 max_len: int = 64, batch_size: int = 16,
                 task: str = "seq_classification",
                 mask_prob: float = 0.15, seed: int = 0):
        self.tok = tokenizer
        self.sentences = list(sentences)
        self.labels = None if labels is None else list(labels)
        self.num_classes = num_classes
        self.max_len = max_len
        self._bs = batch_size
        self.task = task
        self.mask_prob = mask_prob
        self.seed = seed
        self._epoch = 0

    @property
    def batch_size(self):
        return self._bs

    def _encode_one(self, text: str) -> Tuple[np.ndarray, np.ndarray]:
        v = self.tok.vocab
        ids = [v[CLS]] + self.tok.encode(text)[: self.max_len - 2] + [v[SEP]]
        mask = [1] * len(ids)
        while len(ids) < self.max_len:
            ids.append(v[PAD])
            mask.append(0)
        return np.array(ids, np.int32), np.array(mask, np.int32)

    def __iter__(self):
        rng = np.random.RandomState(self.seed + self._epoch)
        self._epoch += 1
        order = rng.permutation(len(self.sentences))
        v = self.tok.vocab
        vocab_size = len(v)
        for i in range(0, len(order), self._bs):
            idx = order[i : i + self._bs]
            ids = np.stack([self._encode_one(self.sentences[j])[0] for j in idx])
            masks = np.stack([self._encode_one(self.sentences[j])[1] for j in idx])
            seg = np.zeros_like(ids)
            if self.task == "seq_classification":
                labs = np.zeros((len(idx), self.num_classes), np.float32)
                for r, j in enumerate(idx):
                    labs[r, self.labels[j]] = 1.0
                yield {"ids": ids, "segments": seg, "mask": masks, "labels": labs}
            else:  # unsupervised MLM
                mlm_ids = ids.copy()
                mlm_labels = np.zeros_like(ids)
                mlm_mask = np.zeros(ids.shape, np.float32)
                sel = (rng.rand(*ids.shape) < self.mask_prob) & (masks > 0)
                sel &= (ids != v[CLS]) & (ids != v[SEP])
                for r in range(ids.shape[0]):
                    for c in np.where(sel[r])[0]:
                        mlm_labels[r, c] = ids[r, c]
                        mlm_mask[r, c] = 1.0
                        p = rng.rand()
                        if p < 0.8:
                            mlm_ids[r, c] = v[MASK]
                        elif p < 0.9:
                            mlm_ids[r, c] = rng.randint(len(SPECIALS), vocab_size)
                yield {"ids": mlm_ids, "segments": seg, "mask": masks,
                       "mlm_labels": mlm_labels, "mlm_mask": mlm_mask}
