"""WordVectorSerializer — interchange formats for word vectors.

Reference parity: org/deeplearning4j/models/embeddings/loader/
WordVectorSerializer.java — the reference reads/writes the Google word2vec
C formats (binary + text) and its own CSV-ish text form, and
``loadStaticModel`` gives a read-only lookup table. Implemented here:

  * write_word2vec_binary / read_word2vec_binary — the Google C binary
    format: "<vocab> <dim>\\n" header then per word "word<space>" + dim
    float32 little-endian values (+ trailing newline, tolerated on read).
  * write_word2vec_text / read_word2vec_text — the text format: header
    line then "word v1 v2 ..." rows.
  * load_static_model — either format → StaticWordVectors (read-only
    lookup: word2vec(), similarity(), words_nearest()).

These interop with gensim/fastText-style tooling, exactly the property the
reference's serializer exists for.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _vectors_of(model) -> Tuple[List[str], np.ndarray]:
    """Accept a Word2Vec (syn0), GloVe (W), ParagraphVectors (word side),
    or a plain (words, matrix) pair."""
    if isinstance(model, tuple):
        words, mat = model
        return list(words), np.asarray(mat, np.float32)
    words = list(model.inv_vocab)
    for attr in ("syn0", "W"):
        mat = getattr(model, attr, None)
        if mat is not None:
            return words, np.asarray(mat, np.float32)
    raise TypeError(
        f"{type(model).__name__} carries no exportable word vectors "
        f"(expected .syn0 or .W, or pass (words, matrix))")


def write_word2vec_binary(model, path: str) -> None:
    """WordVectorSerializer.writeWord2VecModel (binary) analog."""
    words, mat = _vectors_of(model)
    # graftlife: justified(GR005): caller-owned export path, not repo durable
    # state — a torn export is visibly truncated and simply re-exported
    with open(path, "wb") as f:
        f.write(f"{len(words)} {mat.shape[1]}\n".encode("utf-8"))
        for w, row in zip(words, mat):
            f.write(w.encode("utf-8") + b" ")
            f.write(np.ascontiguousarray(row, "<f4").tobytes())
            f.write(b"\n")


def read_word2vec_binary(path: str) -> Tuple[List[str], np.ndarray]:
    """readWord2VecModel (binary) analog — whole-buffer scan (a 3M-word
    GoogleNews file parses in seconds, not the minutes a byte-at-a-time
    loop would take); tolerant of the optional newline between rows that
    the original C tool emits."""
    with open(path, "rb") as f:
        data = f.read()
    nl = data.find(b"\n")
    if nl < 0:
        raise ValueError("truncated word2vec binary header")
    vocab, dim = (int(x) for x in data[:nl].split())
    words: List[str] = []
    mat = np.empty((vocab, dim), np.float32)
    pos = nl + 1
    row_bytes = 4 * dim
    for i in range(vocab):
        while pos < len(data) and data[pos:pos + 1] in (b"\n", b"\r"):
            pos += 1  # inter-row newline variants
        sp = data.find(b" ", pos)
        if sp < 0 or sp + 1 + row_bytes > len(data):
            raise ValueError(f"truncated at word {i}")
        words.append(data[pos:sp].decode("utf-8"))
        mat[i] = np.frombuffer(data, "<f4", count=dim, offset=sp + 1)
        pos = sp + 1 + row_bytes
    return words, mat


def write_word2vec_text(model, path: str) -> None:
    """writeWordVectors (text) analog."""
    words, mat = _vectors_of(model)
    # graftlife: justified(GR005): caller-owned export path, not repo durable
    # state — a torn export is visibly truncated and simply re-exported
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{len(words)} {mat.shape[1]}\n")
        for w, row in zip(words, mat):
            f.write(w + " " + " ".join(repr(float(v)) for v in row) + "\n")


def read_word2vec_text(path: str) -> Tuple[List[str], np.ndarray]:
    with open(path, encoding="utf-8") as f:
        first = f.readline().split()
        words: List[str] = []
        rows: List[np.ndarray] = []
        if len(first) == 2 and all(t.isdigit() for t in first):
            vocab, dim = int(first[0]), int(first[1])
        else:  # headerless glove-style text is accepted too
            vocab, dim = -1, len(first) - 1
            words.append(first[0])
            rows.append(np.asarray([float(v) for v in first[1:]], np.float32))
        for ln in f:
            parts = ln.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append(np.asarray([float(v) for v in parts[1:]], np.float32))
    mat = np.stack(rows) if rows else np.zeros((0, max(dim, 0)), np.float32)
    if vocab >= 0 and len(words) != vocab:
        raise ValueError(f"header declared {vocab} words, file has {len(words)}")
    return words, mat


class StaticWordVectors:
    """loadStaticModel analog: read-only lookup over loaded vectors."""

    def __init__(self, words: Sequence[str], matrix: np.ndarray):
        self.inv_vocab = list(words)
        self.vocab: Dict[str, int] = {w: i for i, w in enumerate(self.inv_vocab)}
        self.syn0 = np.asarray(matrix, np.float32)
        self._norms = np.linalg.norm(self.syn0, axis=1) + 1e-12

    def has_word(self, word: str) -> bool:
        return word in self.vocab

    def word2vec(self, word: str) -> np.ndarray:
        return self.syn0[self.vocab[word]]

    get_word_vector = word2vec  # reference alias

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.word2vec(a), self.word2vec(b)
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        v = self.word2vec(word)
        sims = self.syn0 @ v / (self._norms * (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        return [self.inv_vocab[i] for i in order
                if self.inv_vocab[i] != word][:n]


def load_static_model(path: str) -> StaticWordVectors:
    """Sniff binary vs text (the reference's loadStaticModel dispatch)."""
    with open(path, "rb") as f:
        header = f.readline()
        probe = f.read(256)
    try:
        header.decode("utf-8")
        is_text = True
        try:
            probe.decode("utf-8")
        except UnicodeDecodeError:
            is_text = False
    except UnicodeDecodeError:
        is_text = False
    words, mat = (read_word2vec_text(path) if is_text
                  else read_word2vec_binary(path))
    return StaticWordVectors(words, mat)
