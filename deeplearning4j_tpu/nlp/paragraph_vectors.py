"""ParagraphVectors (doc2vec) on the Word2Vec SGNS substrate.

Reference parity: deeplearning4j-nlp models/paragraphvectors/
ParagraphVectors.java — Builder mirrors Word2Vec's plus labels; PV-DBOW
(dbow=true, the reference default sequence-learning algorithm): a document
vector is trained to predict the words of its document with negative
sampling; ``inferVector`` gradient-fits a fresh vector for an unseen
document against the FROZEN word output matrix.

TPU-native realization: same collapse as Word2Vec — host-side mining of
(doc, word, negatives) triples into large batches, one jitted batched
SGNS step on-device (the reference's per-document threads disappear)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class LabelledDocument:
    """nlp LabelledDocument analog: tokens + a label."""

    def __init__(self, tokens: Sequence[str], label: str):
        self.tokens = list(tokens)
        self.label = label


class ParagraphVectors:
    """ParagraphVectors.java analog (PV-DBOW)."""

    def __init__(self, layer_size: int = 100, min_word_frequency: int = 1,
                 negative_samples: int = 5, learning_rate: float = 0.025,
                 epochs: int = 5, batch_size: int = 2048, seed: int = 42,
                 window_size: int = 5):
        self.layer_size = layer_size
        self.min_count = min_word_frequency
        self.negative = negative_samples
        self.lr = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.window = window_size
        self.labels: Dict[str, int] = {}
        self.inv_labels: List[str] = []
        self._w2v = Word2Vec(layer_size=layer_size,
                             min_word_frequency=min_word_frequency,
                             negative_samples=negative_samples, seed=seed,
                             window_size=window_size)
        self.doc_vectors: Optional[np.ndarray] = None
        self.syn1: Optional[jnp.ndarray] = None  # word OUTPUT matrix
        self._neg_table: Optional[np.ndarray] = None

    # ----------------------------------------------------------------- fit
    def _mine(self, docs: List[LabelledDocument],
              rng: np.random.RandomState) -> Tuple[np.ndarray, np.ndarray]:
        vocab = self._w2v.vocab
        d_idx, w_idx = [], []
        for doc in docs:
            di = self.labels[doc.label]
            for w in doc.tokens:
                i = vocab.get(w.lower())
                if i is not None:
                    d_idx.append(di)
                    w_idx.append(i)
        return np.asarray(d_idx, np.int32), np.asarray(w_idx, np.int32)

    def _make_step(self):
        # graftshape: justified(GS001): PV-DBOW negative-sampling step — batch geometry fixed by the training config, one compile per fit
        @jax.jit
        def step(docv, syn1, docs, words, negs, lr):
            v = docv[docs]
            u_pos = syn1[words]
            u_neg = syn1[negs]
            pos = jnp.sum(v * u_pos, axis=-1)
            neg = jnp.einsum("bd,bkd->bk", v, u_neg)
            g_pos = jax.nn.sigmoid(pos) - 1.0
            g_neg = jax.nn.sigmoid(neg)
            grad_v = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
            grad_upos = g_pos[:, None] * v
            grad_uneg = g_neg[..., None] * v[:, None, :]
            loss = -(jnp.mean(jax.nn.log_sigmoid(pos))
                     + jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg), axis=-1)))
            nd = docv.shape[0]
            acc = jnp.zeros_like(docv).at[docs].add(grad_v)
            cnt = jnp.zeros((nd,), v.dtype).at[docs].add(1.0)
            docv = docv - lr * acc / jnp.maximum(cnt, 1.0)[:, None]
            V = syn1.shape[0]
            nf = negs.reshape(-1)
            acc1 = (jnp.zeros_like(syn1).at[words].add(grad_upos)
                    .at[nf].add(grad_uneg.reshape(-1, grad_uneg.shape[-1])))
            cnt1 = (jnp.zeros((V,), v.dtype).at[words].add(1.0).at[nf].add(1.0))
            syn1 = syn1 - lr * acc1 / jnp.maximum(cnt1, 1.0)[:, None]
            return docv, syn1, loss

        return step

    def fit(self, docs: Iterable[LabelledDocument]) -> List[float]:
        docs = list(docs)
        self.labels = {}
        self.inv_labels = []
        for d in docs:
            if d.label not in self.labels:
                self.labels[d.label] = len(self.labels)
                self.inv_labels.append(d.label)
        self._w2v.build_vocab([d.tokens for d in docs])
        rng = np.random.RandomState(self.seed)
        V, D, ND = self._w2v.vocab_size(), self.layer_size, len(self.labels)
        counts = self._w2v.counts
        table = (counts ** 0.75)
        self._neg_table = (table / table.sum()).astype(np.float64)
        docv = jnp.asarray(((rng.rand(ND, D) - 0.5) / D).astype(np.float32))
        syn1 = jnp.zeros((V, D), jnp.float32)
        step = self._make_step()
        d_idx, w_idx = self._mine(docs, rng)
        n = len(d_idx)
        bs = min(self.batch_size, max(n, 1))
        losses: List[float] = []
        for _ in range(self.epochs):
            order = rng.permutation(n)
            ep = []
            for s0 in range(0, n - bs + 1, bs):
                sel = order[s0:s0 + bs]
                negs = rng.choice(V, size=(len(sel), self.negative),
                                  p=self._neg_table).astype(np.int32)
                docv, syn1, loss = step(docv, syn1,
                                        jnp.asarray(d_idx[sel]),
                                        jnp.asarray(w_idx[sel]),
                                        jnp.asarray(negs),
                                        jnp.float32(self.lr))
                ep.append(float(loss))
            losses.append(float(np.mean(ep)) if ep else float("nan"))
        self.doc_vectors = np.asarray(docv)
        self.syn1 = syn1
        return losses

    # ----------------------------------------------------------- inference
    def infer_vector(self, tokens: Sequence[str], steps: int = 25,
                     lr: float = 0.05, seed: int = 0) -> np.ndarray:
        """inferVector analog: gradient-fit a fresh doc vector against the
        frozen word output matrix."""
        rng = np.random.RandomState(seed)
        ids = np.asarray([self._w2v.vocab[w.lower()] for w in tokens
                          if w.lower() in self._w2v.vocab], np.int32)
        D = self.layer_size
        if ids.size == 0:
            return np.zeros((D,), np.float32)
        v = jnp.asarray(((rng.rand(D) - 0.5) / D).astype(np.float32))
        syn1 = self.syn1
        V = syn1.shape[0]
        one = self._infer_step_cached()
        for _ in range(steps):
            negs = rng.choice(V, size=(len(ids), self.negative),
                              p=self._neg_table).astype(np.int32)
            v = one(v, syn1, jnp.asarray(ids), jnp.asarray(negs),
                    jnp.float32(lr))
        return np.asarray(v)

    def _infer_step_cached(self):
        """One jitted infer step, built once — syn1 is an ARGUMENT so the
        compiled function is reused across infer_vector calls (a closure
        over syn1 would recompile per call)."""
        fn = getattr(self, "_infer_step", None)
        if fn is None:
            D = self.layer_size

            # graftshape: justified(GS001): infer-vector inner step — per-document inference jit with config-fixed negative-sample geometry
            @jax.jit
            def fn(v, syn1, words, negs, lr):
                u_pos = syn1[words]
                u_neg = syn1[negs]
                pos = u_pos @ v
                neg = u_neg.reshape(-1, D) @ v
                g_pos = jax.nn.sigmoid(pos) - 1.0
                g_neg = jax.nn.sigmoid(neg)
                grad = (g_pos[:, None] * u_pos).sum(0) + \
                       (g_neg[:, None] * u_neg.reshape(-1, D)).sum(0)
                return v - lr * grad / words.shape[0]

            self._infer_step = fn
        return fn

    # ------------------------------------------------------------- lookups
    def get_doc_vector(self, label: str) -> Optional[np.ndarray]:
        i = self.labels.get(label)
        return None if i is None else self.doc_vectors[i]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_doc_vector(a), self.get_doc_vector(b)
        if va is None or vb is None:
            return float("nan")
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def nearest_labels(self, tokens: Sequence[str], n: int = 5) -> List[str]:
        """docsNearest-style lookup for an unseen document."""
        v = self.infer_vector(tokens)
        W = self.doc_vectors / (np.linalg.norm(self.doc_vectors, axis=1,
                                               keepdims=True) + 1e-12)
        sims = W @ (v / (np.linalg.norm(v) + 1e-12))
        return [self.inv_labels[i] for i in np.argsort(-sims)[:n]]
