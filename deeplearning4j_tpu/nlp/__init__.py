"""NLP — tokenization, BERT data pipeline, word2vec (deeplearning4j-nlp role)."""

from deeplearning4j_tpu.nlp.wordpiece import (
    BertWordPieceTokenizer,
    BertIterator,
    build_vocab,
)
