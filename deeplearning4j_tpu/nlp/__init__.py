"""NLP — tokenization, BERT data pipeline, word2vec (deeplearning4j-nlp role)."""

from deeplearning4j_tpu.nlp.wordpiece import (
    BertWordPieceTokenizer,
    BertIterator,
    build_vocab,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.glove import GloVe
from deeplearning4j_tpu.nlp.paragraph_vectors import (
    LabelledDocument,
    ParagraphVectors,
)
