"""NLP — tokenization, BERT data pipeline, word2vec (deeplearning4j-nlp role)."""

from deeplearning4j_tpu.nlp.wordpiece import (
    BertWordPieceTokenizer,
    BertIterator,
    build_vocab,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, distributed_word2vec_fit
from deeplearning4j_tpu.nlp.glove import GloVe
from deeplearning4j_tpu.nlp.paragraph_vectors import (
    LabelledDocument,
    ParagraphVectors,
)
from deeplearning4j_tpu.nlp.serde import (
    StaticWordVectors,
    load_static_model,
    read_word2vec_binary,
    read_word2vec_text,
    write_word2vec_binary,
    write_word2vec_text,
)
