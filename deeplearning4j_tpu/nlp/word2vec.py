"""Word2Vec — skip-gram with negative sampling.

Reference parity:
  * deeplearning4j-nlp models/word2vec/** — Word2Vec.Builder (minWordFrequency,
    windowSize, layerSize, negativeSample, iterations/epochs, seed),
    vocab building, `fit()`, `getWordVector`, `wordsNearest`, `similarity`;
    ParagraphVectors sits on the same machinery.

TPU-native realization: the reference trains with per-word Java threads doing
tiny hogwild updates; here (center, context, negatives) triples are mined
host-side into big batches and ONE jitted step does the batched dot-product
sigmoid updates on-device — same objective, MXU-shaped.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Word2Vec:
    """Skip-gram negative-sampling word embeddings."""

    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 min_word_frequency: int = 1, negative_samples: int = 5,
                 learning_rate: float = 0.025, epochs: int = 1,
                 batch_size: int = 512, seed: int = 42,
                 subsample: float = 0.0,
                 use_hierarchic_softmax: bool = False):
        self.layer_size = layer_size
        self.window = window_size
        self.min_count = min_word_frequency
        self.negative = negative_samples
        self.lr = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.subsample = subsample
        self.use_hierarchic_softmax = use_hierarchic_softmax
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: List[str] = []
        self.counts: Optional[np.ndarray] = None
        self.syn0: Optional[jnp.ndarray] = None  # input vectors
        self.syn1: Optional[jnp.ndarray] = None  # output vectors
        self._step_fn = None

    # ---------------------------------------------------------------- vocab
    def build_vocab(self, sentences: Iterable[Sequence[str]]) -> None:
        counter = Counter()
        for s in sentences:
            counter.update(w.lower() for w in s)
        items = [(w, c) for w, c in counter.most_common() if c >= self.min_count]
        self.vocab = {w: i for i, (w, c) in enumerate(items)}
        self.inv_vocab = [w for w, _ in items]
        self.counts = np.array([c for _, c in items], np.float64)

    # ----------------------------------------------------- hierarchic softmax
    def _build_huffman(self):
        """Huffman coding of the vocabulary by frequency (the reference's
        useHierarchicSoftmax path — VocabWord points/codes): returns
        (paths (V, L) inner-node ids, codes (V, L) 0/1 bits, mask (V, L))
        padded to the longest code."""
        import heapq

        V = len(self.vocab)
        heap = [(float(c), i) for i, c in enumerate(self.counts)]
        heapq.heapify(heap)
        parent = {}
        bit = {}
        next_id = V  # inner nodes numbered V..2V-2
        while len(heap) > 1:
            c1, n1 = heapq.heappop(heap)
            c2, n2 = heapq.heappop(heap)
            parent[n1], bit[n1] = next_id, 0
            parent[n2], bit[n2] = next_id, 1
            heapq.heappush(heap, (c1 + c2, next_id))
            next_id += 1
        root = heap[0][1] if heap else V
        paths, codes = [], []
        for w in range(V):
            p, c = [], []
            node = w
            while node != root and node in parent:
                c.append(bit[node])
                p.append(parent[node] - V)  # inner-node table index
                node = parent[node]
            paths.append(p[::-1])
            codes.append(c[::-1])
        L = max((len(p) for p in paths), default=1)
        pad_p = np.zeros((V, L), np.int32)
        pad_c = np.zeros((V, L), np.float32)
        mask = np.zeros((V, L), np.float32)
        for w in range(V):
            n = len(paths[w])
            pad_p[w, :n] = paths[w]
            pad_c[w, :n] = codes[w]
            mask[w, :n] = 1.0
        return pad_p, pad_c, mask

    def _make_hs_step(self):
        def step(syn0, syn1, centers, nodes, codes, mask, lr):
            """Batched hierarchical-softmax update: along each context
            word's Huffman path, L = -Σ log σ((1−2·code)·v·u_node)."""
            v = syn0[centers]                        # (B, D)
            u = syn1[nodes]                          # (B, L, D)
            score = jnp.einsum("bd,bld->bl", v, u)   # (B, L)
            sign = 1.0 - 2.0 * codes
            # dL/dscore for L = -log σ(sign·s): σ(s) − 1 for code 0,
            # σ(s) for code 1 → σ(s) − (1 − code)
            g = (jax.nn.sigmoid(score) - (1.0 - codes)) * mask
            loss = -jnp.sum(jax.nn.log_sigmoid(sign * score) * mask) /                 jnp.maximum(jnp.sum(mask), 1.0)
            grad_v = jnp.einsum("bl,bld->bd", g, u)
            grad_u = g[..., None] * v[:, None, :]
            V = syn0.shape[0]
            acc0 = jnp.zeros_like(syn0).at[centers].add(grad_v)
            cnt0 = jnp.zeros((V,), v.dtype).at[centers].add(1.0)
            syn0 = syn0 - lr * acc0 / jnp.maximum(cnt0, 1.0)[:, None]
            flat_nodes = nodes.reshape(-1)
            acc1 = jnp.zeros_like(syn1).at[flat_nodes].add(
                grad_u.reshape(-1, grad_u.shape[-1]))
            cnt1 = jnp.zeros((syn1.shape[0],), v.dtype).at[flat_nodes].add(
                mask.reshape(-1))
            syn1 = syn1 - lr * acc1 / jnp.maximum(cnt1, 1.0)[:, None]
            return syn0, syn1, loss

        # graftshape: justified(GS001): hierarchical-softmax train step — batch shape fixed by batch_size (the ragged tail batch is the GS002 note in fit)
        return jax.jit(step, donate_argnums=(0, 1))

    # ------------------------------------------------------------------ fit
    def _make_step(self):
        neg = self.negative

        def step(syn0, syn1, centers, contexts, negatives, lr):
            """Batched SGNS update: maximize log σ(v·u⁺) + Σ log σ(-v·u⁻)."""
            v = syn0[centers]                      # (B, D)
            u_pos = syn1[contexts]                 # (B, D)
            u_neg = syn1[negatives]                # (B, K, D)
            pos_score = jnp.sum(v * u_pos, axis=-1)            # (B,)
            neg_score = jnp.einsum("bd,bkd->bk", v, u_neg)     # (B, K)
            g_pos = jax.nn.sigmoid(pos_score) - 1.0            # dL/d(pos_score)
            g_neg = jax.nn.sigmoid(neg_score)                  # dL/d(neg_score)
            grad_v = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
            grad_upos = g_pos[:, None] * v
            grad_uneg = g_neg[..., None] * v[:, None, :]
            loss = -(jnp.mean(jax.nn.log_sigmoid(pos_score))
                     + jnp.mean(jnp.sum(jax.nn.log_sigmoid(-neg_score), axis=-1)))
            # per-word MEAN gradient: normalize the scatter-add by how many
            # times each index occurs in the batch, so small vocabularies
            # (many collisions per batch) don't get a multiplied step size
            V = syn0.shape[0]
            acc0 = jnp.zeros_like(syn0).at[centers].add(grad_v)
            cnt0 = jnp.zeros((V,), grad_v.dtype).at[centers].add(1.0)
            syn0 = syn0 - lr * acc0 / jnp.maximum(cnt0, 1.0)[:, None]
            neg_flat = negatives.reshape(-1)
            acc1 = (jnp.zeros_like(syn1).at[contexts].add(grad_upos)
                    .at[neg_flat].add(grad_uneg.reshape(-1, grad_uneg.shape[-1])))
            cnt1 = (jnp.zeros((V,), grad_v.dtype).at[contexts].add(1.0)
                    .at[neg_flat].add(1.0))
            syn1 = syn1 - lr * acc1 / jnp.maximum(cnt1, 1.0)[:, None]
            return syn0, syn1, loss

        # graftshape: justified(GS001): negative-sampling train step — same fixed batch geometry as the HS step
        return jax.jit(step, donate_argnums=(0, 1))

    def _pairs(self, sentences: List[List[str]], rng: np.random.RandomState):
        centers, contexts = [], []
        keep_prob = None
        if self.subsample > 0:
            freq = self.counts / self.counts.sum()
            keep_prob = np.minimum(1.0, np.sqrt(self.subsample / freq)
                                   + self.subsample / freq)
        for s in sentences:
            ids = [self.vocab[w.lower()] for w in s if w.lower() in self.vocab]
            if keep_prob is not None:
                ids = [i for i in ids if rng.rand() < keep_prob[i]]
            for pos, c in enumerate(ids):
                w = rng.randint(1, self.window + 1)
                for off in range(-w, w + 1):
                    j = pos + off
                    if off != 0 and 0 <= j < len(ids):
                        centers.append(c)
                        contexts.append(ids[j])
        return np.asarray(centers, np.int32), np.asarray(contexts, np.int32)

    def fit(self, sentences: Iterable[Sequence[str]]) -> List[float]:
        sentences = [list(s) for s in sentences]
        if not self.vocab:
            self.build_vocab(sentences)
        V, D = len(self.vocab), self.layer_size
        if self.syn0 is None or self.syn0.shape != (V, D):
            # fresh init only when untrained (a loaded/partially-trained model
            # continues from its existing vectors, reference semantics)
            key = jax.random.key(self.seed)
            self.syn0 = (jax.random.uniform(key, (V, D)) - 0.5) / D
            self.syn1 = jnp.zeros((V, D))
        if self.use_hierarchic_softmax:
            return self._fit_hs(sentences)
        if self._step_fn is None:
            self._step_fn = self._make_step()
        # unigram^0.75 negative-sampling table (reference's table approach)
        probs = self.counts ** 0.75
        probs = probs / probs.sum()
        rng = np.random.RandomState(self.seed)
        history = []
        for ep in range(self.epochs):
            centers, contexts = self._pairs(sentences, rng)
            order = rng.permutation(len(centers))
            losses = []
            lr = self.lr * max(0.0001, 1.0 - ep / max(self.epochs, 1))
            for i in range(0, len(order), self.batch_size):
                idx = order[i : i + self.batch_size]
                if len(idx) < 2:
                    continue
                negs = rng.choice(len(probs), size=(len(idx), self.negative), p=probs)
                self.syn0, self.syn1, loss = self._step_fn(
                    self.syn0, self.syn1, jnp.asarray(centers[idx]),
                    jnp.asarray(contexts[idx]), jnp.asarray(negs, jnp.int32),
                    jnp.asarray(lr, jnp.float32))
                losses.append(loss)
            history.append(float(jnp.mean(jnp.stack(losses))) if losses else float("nan"))
        return history

    def _fit_hs(self, sentences: List[List[str]]) -> List[float]:
        """Hierarchical-softmax training (useHierarchicSoftmax=true)."""
        V, D = len(self.vocab), self.layer_size
        paths, codes, mask = self._build_huffman()
        # syn1 here is the INNER-NODE table (V-1 rows), reference syn1 role
        self.syn1 = jnp.zeros((max(V - 1, 1), D))
        step = self._make_hs_step()
        rng = np.random.RandomState(self.seed)
        paths_j, codes_j, mask_j = (jnp.asarray(paths), jnp.asarray(codes),
                                    jnp.asarray(mask))
        history = []
        for ep in range(self.epochs):
            centers, contexts = self._pairs(sentences, rng)
            order = rng.permutation(len(centers))
            losses = []
            lr = self.lr * max(0.0001, 1.0 - ep / max(self.epochs, 1))
            for i in range(0, len(order), self.batch_size):
                idx = order[i : i + self.batch_size]
                if len(idx) < 2:
                    continue
                ctx = contexts[idx]
                # graftshape: justified(GS002): the permutation TAIL batch is the one ragged shape — at most one extra trace per corpus (len % batch_size), accepted; padding it would change the HS loss math
                self.syn0, self.syn1, loss = step(
                    self.syn0, self.syn1, jnp.asarray(centers[idx]),
                    paths_j[ctx], codes_j[ctx], mask_j[ctx],
                    jnp.asarray(lr, jnp.float32))
                losses.append(loss)
            history.append(float(jnp.mean(jnp.stack(losses))) if losses else float("nan"))
        return history

    # ------------------------------------------------------------- queries
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.get(word.lower())
        return None if i is None else np.asarray(self.syn0[i])

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        v = self.get_word_vector(word)
        if v is None:
            return []
        mat = np.asarray(self.syn0)
        sims = mat @ v / (np.linalg.norm(mat, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out = [self.inv_vocab[i] for i in order if self.inv_vocab[i] != word.lower()]
        return out[:n]

    def vocab_size(self) -> int:
        return len(self.vocab)

    # --------------------------------------------------------------- serde
    def save(self, path: str) -> None:
        np.savez(path, syn0=np.asarray(self.syn0), syn1=np.asarray(self.syn1),
                 vocab=np.array(self.inv_vocab, dtype=object),
                 counts=self.counts)

    @staticmethod
    def load(path: str) -> "Word2Vec":
        data = np.load(path, allow_pickle=True)
        w = Word2Vec(layer_size=int(data["syn0"].shape[1]))
        w.inv_vocab = list(data["vocab"])
        w.vocab = {v: i for i, v in enumerate(w.inv_vocab)}
        w.counts = data["counts"]
        w.syn0 = jnp.asarray(data["syn0"])
        w.syn1 = jnp.asarray(data["syn1"])
        return w


def distributed_word2vec_fit(w2v: "Word2Vec", sentences, *, epochs=None):
    """Cluster word2vec — the dl4j-spark-nlp SparkWord2Vec role (SURVEY
    §3.3): the corpus shards per host (deterministic sentence round-robin,
    the RDD-partition analog), every rank trains its shard locally for one
    epoch, then the embedding matrices PARAMETER-AVERAGE across the
    cluster — the same sync-averaging semantics the reference's Spark
    training master applies to word vectors.

    The vocabulary must be identical on every rank, so it is built from the
    FULL corpus on each host (vocab building is a cheap counting pass; the
    expensive part — training — runs on 1/N of the pairs per host).
    Single-process runs degrade to a plain fit."""
    import jax

    sentences = [list(s) for s in sentences]
    if not w2v.vocab:
        w2v.build_vocab(sentences)
    epochs = epochs if epochs is not None else w2v.epochs
    n = jax.process_count()
    if n == 1:
        saved = w2v.epochs
        w2v.epochs = epochs
        try:
            return w2v.fit(sentences)
        finally:
            w2v.epochs = saved
    if w2v.use_hierarchic_softmax:
        # fit() re-derives the HS tree and zeroes syn1 on every call, which
        # would discard the averaged inner-node table each epoch
        raise NotImplementedError(
            "distributed_word2vec_fit supports negative sampling only "
            "(hierarchical softmax rebuilds syn1 per fit call)")
    from deeplearning4j_tpu.parallel.launch import host_shard

    from jax.experimental import multihost_utils

    shard = host_shard(sentences)
    # every rank must hold initialized matrices BEFORE the collectives —
    # an empty-shard rank never calls fit() and would otherwise crash out
    # of the allgather, deadlocking the cluster
    V, D = len(w2v.vocab), w2v.layer_size
    if w2v.syn0 is None or w2v.syn0.shape != (V, D):
        key = jax.random.key(w2v.seed)
        w2v.syn0 = (jax.random.uniform(key, (V, D), jnp.float32) - 0.5) / D
        w2v.syn1 = jnp.zeros((V, D), jnp.float32)
    losses = []
    saved_epochs = w2v.epochs
    w2v.epochs = 1
    try:
        for _ in range(epochs):
            if shard:
                losses.extend(w2v.fit(shard))
            # parameter averaging over the cluster
            for attr in ("syn0", "syn1"):
                gathered = multihost_utils.process_allgather(
                    np.asarray(getattr(w2v, attr), np.float32))
                setattr(w2v, attr, jnp.asarray(
                    np.asarray(gathered).mean(axis=0)))
    finally:
        w2v.epochs = saved_epochs
    return losses
