"""GloVe — global co-occurrence factorization embeddings.

Reference parity: deeplearning4j-nlp models/glove/** (Glove.Builder —
layerSize, windowSize, minWordFrequency, xMax, alpha, learningRate,
epochs; AbstractCoOccurrences builds the weighted co-occurrence counts,
GloveWeightLookupTable trains with per-parameter AdaGrad).

TPU-native realization: the reference shards co-occurrence accumulation
and training across Java threads; here the co-occurrence table is built
host-side into COO arrays once, and every epoch runs batched jitted
AdaGrad steps over shuffled nonzero pairs — the weighted-least-squares
objective J = Σ f(X_ij)(wᵢ·w̃ⱼ + bᵢ + b̃ⱼ − log X_ij)², identical math,
MXU-shaped batches."""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class GloVe:
    """Glove.java analog (same knob names, snake_cased)."""

    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 min_word_frequency: int = 1, x_max: float = 100.0,
                 alpha: float = 0.75, learning_rate: float = 0.05,
                 epochs: int = 25, batch_size: int = 4096, seed: int = 42,
                 symmetric: bool = True):
        self.layer_size = layer_size
        self.window = window_size
        self.min_count = min_word_frequency
        self.x_max = x_max
        self.alpha = alpha
        self.lr = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.symmetric = symmetric
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: List[str] = []
        self.W: Optional[np.ndarray] = None  # final vectors (w + w̃)

    # ---------------------------------------------------------------- vocab
    def build_vocab(self, sentences: Iterable[Sequence[str]]) -> None:
        counter = Counter()
        for s in sentences:
            counter.update(w.lower() for w in s)
        items = [(w, c) for w, c in counter.most_common()
                 if c >= self.min_count]
        self.vocab = {w: i for i, (w, _) in enumerate(items)}
        self.inv_vocab = [w for w, _ in items]

    def _cooccurrences(self, sentences: List[List[str]]):
        """AbstractCoOccurrences analog: window counts weighted 1/distance."""
        cooc: Dict[tuple, float] = defaultdict(float)
        for s in sentences:
            ids = [self.vocab[w.lower()] for w in s if w.lower() in self.vocab]
            for pos, ci in enumerate(ids):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(ids):
                        break
                    w = 1.0 / off
                    cooc[(ci, ids[j])] += w
                    if self.symmetric:
                        cooc[(ids[j], ci)] += w
        rows = np.fromiter((k[0] for k in cooc), np.int32, len(cooc))
        cols = np.fromiter((k[1] for k in cooc), np.int32, len(cooc))
        vals = np.fromiter(cooc.values(), np.float32, len(cooc))
        return rows, cols, vals

    # ------------------------------------------------------------------ fit
    def fit(self, sentences: Iterable[Sequence[str]]) -> List[float]:
        sentences = [list(s) for s in sentences]
        if not self.vocab:
            self.build_vocab(sentences)
        rows, cols, vals = self._cooccurrences(sentences)
        V, D = len(self.vocab), self.layer_size
        rng = np.random.RandomState(self.seed)
        scale = 0.5 / D
        w = jnp.asarray(rng.uniform(-scale, scale, (V, D)).astype(np.float32))
        wc = jnp.asarray(rng.uniform(-scale, scale, (V, D)).astype(np.float32))
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        # AdaGrad accumulators (GloveWeightLookupTable historical gradients)
        hw = jnp.ones((V, D), jnp.float32)
        hwc = jnp.ones((V, D), jnp.float32)
        hb = jnp.ones((V,), jnp.float32)
        hbc = jnp.ones((V,), jnp.float32)
        logx = jnp.asarray(np.log(vals))
        fx = jnp.asarray(np.minimum((vals / self.x_max) ** self.alpha, 1.0)
                         .astype(np.float32))
        rows_j = jnp.asarray(rows)
        cols_j = jnp.asarray(cols)
        lr = self.lr

        # graftshape: justified(GS001): whole-epoch scan step over a fixed co-occurrence table — exactly one compile per fit
        @jax.jit
        def epoch_step(state, order):
            def batch_step(state, idx):
                w, wc, b, bc, hw, hwc, hb, hbc = state
                i = rows_j[idx]
                j = cols_j[idx]
                diff = (jnp.sum(w[i] * wc[j], axis=-1) + b[i] + bc[j]
                        - logx[idx])
                fdiff = fx[idx] * diff
                loss = jnp.mean(fdiff * diff)
                gw = fdiff[:, None] * wc[j]
                gwc = fdiff[:, None] * w[i]

                def adagrad(p, h, g, ix):
                    h = h.at[ix].add(g * g)
                    return p.at[ix].add(-lr * g / jnp.sqrt(h[ix])), h

                w, hw = adagrad(w, hw, gw, i)
                wc, hwc = adagrad(wc, hwc, gwc, j)
                b, hb = adagrad(b, hb, fdiff, i)
                bc, hbc = adagrad(bc, hbc, fdiff, j)
                return (w, wc, b, bc, hw, hwc, hb, hbc), loss

            return jax.lax.scan(batch_step, state, order)

        n = len(vals)
        if n == 0:
            self.W = np.zeros((V, D), np.float32)
            return []  # nothing co-occurred (empty corpus / all filtered)
        bs = min(self.batch_size, n)
        losses: List[float] = []
        state = (w, wc, b, bc, hw, hwc, hb, hbc)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            nb = n // bs
            batches = jnp.asarray(order[:nb * bs].reshape(nb, bs))
            state, ls = epoch_step(state, batches)
            losses.append(float(jnp.mean(ls)))
        w, wc = state[0], state[1]
        self.W = np.asarray(w + wc)  # the published GloVe convention
        return losses

    # ------------------------------------------------------------- lookups
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.get(word.lower())
        return None if i is None else self.W[i]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        v = self.get_word_vector(word)
        if v is None:
            return []
        W = self.W / (np.linalg.norm(self.W, axis=1, keepdims=True) + 1e-12)
        sims = W @ (v / (np.linalg.norm(v) + 1e-12))
        idx = np.argsort(-sims)
        out = [self.inv_vocab[i] for i in idx if self.inv_vocab[i] != word.lower()]
        return out[:n]

    def vocab_size(self) -> int:
        return len(self.vocab)
