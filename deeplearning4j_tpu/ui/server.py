"""Training UI server — the VertxUIServer + DL4J training dashboard role.

Reference parity: deeplearning4j-ui's VertxUIServer serves the train
dashboard (score chart, update:parameter ratio chart — "the signature
debugging tool" per SURVEY §6.5) over attached StatsStorage instances
(UIServer.getInstance().attach(statsStorage)).

TPU-native realization: a stdlib http.server on a daemon thread (no web
framework in the environment) serving

  * ``/``                 — single-page dashboard, dependency-free inline
                            SVG charts, auto-refreshing
  * ``/train/sessions``   — attached session ids
  * ``/train/overview``   — score-vs-iteration series
  * ``/train/model``      — per-parameter update:param-ratio + norm series
  * ``/metrics``          — Prometheus text exposition of the process-wide
                            observe/ registry (docs/OBSERVABILITY.md)

against the same StatsStorage records StatsListener emits, so the usage
mirrors the reference exactly:

    storage = StatsStorage()
    UIServer.get_instance().attach(storage)
    net.set_listeners(StatsListener(storage))
    net.fit(...)   # browse http://localhost:9000
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from deeplearning4j_tpu.utils.stats import StatsStorage

_INSTANCE: Optional["UIServer"] = None

_PAGE = """<!DOCTYPE html>
<html><head><title>DL4J-TPU Training UI</title>
<style>
 body { font-family: sans-serif; margin: 1.5em; background: #fafafa; }
 h1 { font-size: 1.2em; } h2 { font-size: 1em; color: #444; }
 .card { background: #fff; border: 1px solid #ddd; border-radius: 6px;
         padding: 1em; margin-bottom: 1.2em; }
 svg { width: 100%%; height: 260px; }
 .legend { font-size: 0.8em; color: #666; }
</style></head>
<body>
<h1>DL4J-TPU Training UI</h1>
<div class="card"><h2>Model score vs. iteration</h2>
 <svg id="score"></svg></div>
<div class="card"><h2>Update : parameter ratio (log10) — healthy ≈ −3</h2>
 <svg id="ratio"></svg><div id="ratio-legend" class="legend"></div></div>
<div class="card"><h2>Parameter histograms (latest iteration)</h2>
 <div id="hists" class="legend">enable StatsListener(collect_histograms=True)</div></div>
<div class="card"><h2>Model graph</h2>
 <svg id="graph" style="height:auto"></svg></div>
<script>
const COLORS = ['#1976d2','#d32f2f','#388e3c','#f57c00','#7b1fa2',
                '#00796b','#5d4037','#455a64','#c2185b','#afb42b'];
function drawSeries(svgId, seriesMap, legendId) {
  const svg = document.getElementById(svgId);
  const W = svg.clientWidth || 800, H = svg.clientHeight || 260, P = 36;
  let xs = [], ys = [];
  for (const k in seriesMap) {
    seriesMap[k].forEach(p => { xs.push(p[0]); ys.push(p[1]); });
  }
  if (!xs.length) { svg.innerHTML = '<text x="20" y="30">waiting for data…</text>'; return; }
  const xmin = Math.min(...xs), xmax = Math.max(...xs) || 1;
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const sx = x => P + (x - xmin) / Math.max(xmax - xmin, 1e-9) * (W - 2*P);
  const sy = y => H - P - (y - ymin) / Math.max(ymax - ymin, 1e-9) * (H - 2*P);
  let out = `<line x1="${P}" y1="${H-P}" x2="${W-P}" y2="${H-P}" stroke="#999"/>` +
            `<line x1="${P}" y1="${P}" x2="${P}" y2="${H-P}" stroke="#999"/>` +
            `<text x="${P}" y="${H-6}" font-size="10">${xmin}</text>` +
            `<text x="${W-P-30}" y="${H-6}" font-size="10">${xmax}</text>` +
            `<text x="2" y="${H-P}" font-size="10">${ymin.toFixed(3)}</text>` +
            `<text x="2" y="${P+4}" font-size="10">${ymax.toFixed(3)}</text>`;
  let i = 0, legend = [];
  for (const k in seriesMap) {
    const c = COLORS[i++ % COLORS.length];
    const pts = seriesMap[k].map(p => `${sx(p[0])},${sy(p[1])}`).join(' ');
    out += `<polyline fill="none" stroke="${c}" stroke-width="1.5" points="${pts}"/>`;
    legend.push(`<span style="color:${c}">■</span> ${k}`);
  }
  svg.innerHTML = out;
  if (legendId) document.getElementById(legendId).innerHTML = legend.join(' &nbsp; ');
}
function drawHists(containerId, byParam) {
  const names = Object.keys(byParam);
  if (!names.length) return;
  const div = document.getElementById(containerId);
  let out = '';
  names.forEach((k, i) => {
    const h = byParam[k], counts = h.counts, mx = Math.max(...counts, 1);
    const W = 240, H = 80, bw = W / counts.length;
    const c = COLORS[i % COLORS.length];
    let bars = counts.map((v, j) =>
      `<rect x="${j*bw}" y="${H - v/mx*H}" width="${bw-1}" height="${v/mx*H}" fill="${c}"/>`
    ).join('');
    out += `<div style="display:inline-block;margin:4px"><div>${esc(k)}</div>` +
           `<svg style="width:${W}px;height:${H}px">${bars}</svg></div>`;
  });
  div.innerHTML = out;
}
function esc(s) {
  return String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;')
                  .replace(/>/g, '&gt;');
}
function drawGraph(svgId, g) {
  if (!g || !g.nodes || !g.nodes.length) return;
  const svg = document.getElementById(svgId);
  // layered layout: node depth = 1 + max depth of producers
  const depth = {}, incoming = {};
  g.nodes.forEach(n => depth[n.name] = 0);
  g.edges.forEach(e => (incoming[e[1]] = incoming[e[1]] || []).push(e[0]));
  for (let pass = 0; pass < g.nodes.length; pass++) {
    let changed = false;
    g.nodes.forEach(n => {
      const d = 1 + Math.max(-1, ...(incoming[n.name] || [])
                             .map(p => depth[p] ?? 0));
      if (d > depth[n.name]) { depth[n.name] = d; changed = true; }
    });
    if (!changed) break;
  }
  const byDepth = {};
  g.nodes.forEach(n => (byDepth[depth[n.name]] =
                        byDepth[depth[n.name]] || []).push(n));
  const COLW = 170, ROWH = 44, pos = {};
  let maxRow = 1;
  Object.keys(byDepth).forEach(d => {
    byDepth[d].forEach((n, i) => { pos[n.name] = [d * COLW + 10, i * ROWH + 14]; });
    maxRow = Math.max(maxRow, byDepth[d].length);
  });
  const H = maxRow * ROWH + 30;
  svg.setAttribute('height', H);
  let out = '';
  g.edges.forEach(e => {
    const a = pos[e[0]], b = pos[e[1]];
    if (!a || !b) return;
    out += `<line x1="${a[0]+140}" y1="${a[1]+12}" x2="${b[0]}" y2="${b[1]+12}"
             stroke="#bbb"/>`;
  });
  g.nodes.forEach(n => {
    const p = pos[n.name];
    const label = n.params ? `${esc(n.name)} (${n.params})` : esc(n.name);
    out += `<rect x="${p[0]}" y="${p[1]}" width="140" height="24" rx="4"
             fill="#e3f2fd" stroke="#1976d2"/>` +
           `<text x="${p[0]+6}" y="${p[1]+16}" font-size="10">${label}</text>` +
           `<title>${esc(n.type)}</title>`;
  });
  svg.innerHTML = out;
}
async function refresh() {
  try {
    const ov = await (await fetch('train/overview')).json();
    drawSeries('score', {score: ov.score});
    const m = await (await fetch('train/model')).json();
    drawSeries('ratio', m.update_ratio_log10, 'ratio-legend');
    const hs = await (await fetch('train/histograms')).json();
    drawHists('hists', hs.histograms);
    drawGraph('graph', await (await fetch('train/graph')).json());
  } catch (e) {}
  setTimeout(refresh, 2000);
}
refresh();
</script></body></html>
"""


class UIServer:
    """UIServer.java analog (singleton + attach)."""

    def __init__(self, port: int = 9000):
        self.port = port
        # ThreadingHTTPServer handles each request on its own thread, so
        # attach/detach from the trainer race _records() from handlers —
        # every _storages touch goes through this lock (graftlock GL012)
        self._lock = threading.Lock()
        self._storages: List[StatsStorage] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- reference API -------------------------------------------------------
    @staticmethod
    def get_instance(port: int = 9000) -> "UIServer":
        global _INSTANCE
        if _INSTANCE is None:
            _INSTANCE = UIServer(port)
            _INSTANCE.start()
        return _INSTANCE

    def attach(self, storage: StatsStorage) -> None:
        with self._lock:
            self._storages.append(storage)

    def remote_storage(self) -> StatsStorage:
        """The storage remote workers post into (auto-attached on first
        use) — the receiving half of RemoteUIStatsStorageRouter."""
        with self._lock:
            if not hasattr(self, "_remote_storage"):
                self._remote_storage = StatsStorage()
                self._storages.append(self._remote_storage)
            return self._remote_storage

    def detach(self, storage: StatsStorage) -> None:
        with self._lock:
            if storage in self._storages:
                self._storages.remove(storage)

    # -- data assembly -------------------------------------------------------
    def _records(self) -> List[Dict]:
        recs: List[Dict] = []
        with self._lock:
            storages = list(self._storages)
        for st in storages:
            recs.extend(r for r in getattr(st, "records", [])
                        if "static_model_info" not in r)
        return sorted(recs, key=lambda r: r.get("iteration", 0))

    def overview(self) -> Dict:
        recs = self._records()
        return {"score": [[r["iteration"], r["score"]] for r in recs]}

    def model(self) -> Dict:
        import math

        recs = self._records()
        ratios: Dict[str, List] = {}
        norms: Dict[str, List] = {}
        for r in recs:
            for name, st in r.get("layers", {}).items():
                if not name.endswith("_W"):
                    continue  # the reference charts weight params
                if "update_ratio" in st:
                    ratios.setdefault(name, []).append(
                        [r["iteration"],
                         math.log10(max(st["update_ratio"], 1e-12))])
                norms.setdefault(name, []).append(
                    [r["iteration"], st.get("norm2", 0.0)])
        return {"update_ratio_log10": ratios, "param_norm2": norms}

    def histograms(self) -> Dict:
        """Latest iteration's per-parameter histograms (the reference
        dashboard's parameter/update histogram pane; needs
        StatsListener(collect_histograms=True))."""
        recs = self._records()
        for r in reversed(recs):
            out = {}
            for name, st in r.get("layers", {}).items():
                if "histogram" in st:
                    out[name] = st["histogram"]
            if out:
                return {"iteration": r.get("iteration", 0),
                        "histograms": out}
        return {"iteration": -1, "histograms": {}}

    def graph(self) -> Dict:
        """Model topology (the reference UI's model-graph pane): the
        one-time static_model_info record StatsListener emits."""
        with self._lock:
            storages = list(self._storages)
        for st in storages:
            for r in getattr(st, "records", []):
                if "static_model_info" in r:
                    return r["static_model_info"]
        return {"kind": "none", "nodes": [], "edges": []}

    def sessions(self) -> Dict:
        with self._lock:
            n = len(self._storages)
        return {"sessions": list(range(n)),
                "records": len(self._records())}

    # -- http ---------------------------------------------------------------
    def start(self) -> "UIServer":
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib naming)
                # RemoteUIStatsStorageRouter endpoint: workers (launcher
                # ranks, other hosts) POST JSON stats records here; they land
                # in the server's remote storage and show on the same charts
                if not self.path.rstrip("/").endswith("/remote"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(n) or b"[]")
                    records = payload if isinstance(payload, list) else [payload]
                    for rec in records:
                        ui.remote_storage().put(rec)
                    self.send_response(200)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                except Exception:
                    self.send_response(400)
                    self.end_headers()

            def do_GET(self):  # noqa: N802 (stdlib naming)
                path = self.path.rstrip("/") or "/"
                if path == "/" or path == "/train":
                    body = _PAGE.encode()
                    ctype = "text/html"
                elif path.endswith("/train/sessions"):
                    body = json.dumps(ui.sessions()).encode()
                    ctype = "application/json"
                elif path.endswith("/train/overview"):
                    body = json.dumps(ui.overview()).encode()
                    ctype = "application/json"
                elif path.endswith("/train/model"):
                    body = json.dumps(ui.model()).encode()
                    ctype = "application/json"
                elif path.endswith("/train/histograms"):
                    body = json.dumps(ui.histograms()).encode()
                    ctype = "application/json"
                elif path.endswith("/train/graph"):
                    body = json.dumps(ui.graph()).encode()
                    ctype = "application/json"
                elif path.endswith("/metrics"):
                    # Prometheus text exposition of the process-wide observe/
                    # registry (recompiles, train-step + serving latency
                    # histograms — docs/OBSERVABILITY.md)
                    from deeplearning4j_tpu import observe

                    body = observe.metrics().render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._httpd = ThreadingHTTPServer(("0.0.0.0", self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolves port=0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        global _INSTANCE
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            # shutdown() only signals serve_forever — join so stop()
            # returns with the serve thread actually gone
            self._thread.join(timeout=5.0)
            self._thread = None
        if _INSTANCE is self:
            _INSTANCE = None
