"""Training UI server (deeplearning4j-ui role)."""

from deeplearning4j_tpu.ui.server import UIServer
