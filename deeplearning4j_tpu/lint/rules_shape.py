"""graftshape — static jit-signature & recompile-discipline rules.

The repo's load-bearing invariant — every compiled fn's signature depends
ONLY on server/training config, so serving and resume see zero
``new_shape`` — is enforced at runtime by per-feature assertions. These
rules enforce it at review time:

GS001  unledgered jit: a ``jax.jit`` / ``.lower().compile()`` callsite
       whose returned fn is never registered via
       ``observe.note_jit_signature`` — its recompiles would be
       unattributed in the RecompileLedger
GS002  request-shaped signature: an array argument to a jitted fn whose
       shape derives (intra-module dataflow: ``len()``, ``.shape``,
       ``np.zeros(n)``-style construction, slicing by a non-config
       variable) from request/batch state without passing through a
       recognized bucket/pad helper
GS003  traced-value leak: ``int()/float()/bool()/.item()/np.asarray()``
       or Python ``if``/``while`` on traced values inside jit-decorated
       or jit-reachable code
GS004  weak-type churn: bare Python scalars passed positionally into a
       jitted fn where device arrays flow on other call paths — the
       signature splits on weak types
GS005  static-arg hazard: ``static_argnums``/``static_argnames`` covering
       a value the same module mutates per call — every mutation is a
       recompile

Same house rules as ``rules_ast``/``rules_concurrency``: deliberately
conservative, blind spots documented in docs/LINT.md. A true positive the
code *means* is suppressed inline with ``# graftshape: justified(GS00x):
<reason>`` — the reason is mandatory; a bare marker does not suppress.

Scope: GS001/GS002/GS004/GS005 apply to the package only (paths outside
``tools/``/``examples/`` — standalone bench scripts own their throwaway
jits; the ledger contract covers library code). GS003 is a correctness
rule and applies everywhere.

Beyond the per-file rules this module exports the repo-wide static
jit-boundary inventory (:func:`static_shape_inventory`) that the runtime
recompile tracer (``testing/shapetrace.py``) cross-validates: every
``CompileEvent.callsite`` observed under the randomized-shape workloads
must fall inside a statically known registration site, and every
``new_shape`` event must attribute to a module the analyzer flagged as a
hazard.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.lint.core import Finding, ast_rule, iter_py_files
from deeplearning4j_tpu.lint.rules_ast import (
    _NUMPY_ALIASES, _dotted, _is_jit_expr, _jit_functions)

# ---------------------------------------------------------------------------
# inline justification (the graftshape analog of "graftlock: justified")
# ---------------------------------------------------------------------------

_JUSTIFIED_RE = re.compile(
    r"graftshape:\s*justified\((GS\d{3})\)\s*:\s*(\S.*)")


def _justified_lines(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """1-based line -> rule ids justified there. Only matches carrying a
    nonempty written reason suppress — acceptance requires every justified
    site to say WHY."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, start=1):
        for m in _JUSTIFIED_RE.finditer(text):
            out.setdefault(i, set()).add(m.group(1))
    return out


def _apply_justified(findings: List[Finding],
                     lines: Sequence[str]) -> List[Finding]:
    """A justification suppresses a finding on its own line or on the
    line directly below (comment-above form, for statements too long to
    carry a trailing comment)."""
    just = _justified_lines(lines)
    return [f for f in findings
            if f.rule not in just.get(f.line, ())
            and f.rule not in just.get(f.line - 1, ())]


def _in_library(path: str) -> bool:
    """The ledger-discipline rules cover library code; standalone bench /
    example scripts create deliberately throwaway jits."""
    return not (path.startswith("tools/") or path.startswith("examples/"))


def _is_direct_jit_call(node: ast.AST) -> bool:
    """True for the jit-creating Call itself: ``jax.jit(f)`` / ``pjit(f)``
    (NOT a call through a partial or an already-created handle)."""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    return d is not None and d.split(".")[-1] in ("jit", "pjit")


# ---------------------------------------------------------------------------
# jit dataflow model: where jits are created, where handles flow, where
# they are registered with the ledger
# ---------------------------------------------------------------------------


class _JitSite:
    """One jit-creating expression (a ``jax.jit(...)`` call, a jit
    decorator, or an AOT ``.lower().compile()`` chain rooted in one)."""

    __slots__ = ("index", "line", "name_hint", "static_names", "call_node")

    def __init__(self, index: int, line: int, name_hint: str,
                 static_names: Tuple[str, ...] = (),
                 call_node: Optional[ast.Call] = None):
        self.index = index
        self.line = line
        self.name_hint = name_hint       # wrapped fn name when identifiable
        self.static_names = static_names  # static_argnums/argnames coverage
        self.call_node = call_node       # the jax.jit Call (None: decorator)


class _Scope:
    """One function/method with its jit-value bindings."""

    def __init__(self, cls: Optional[str], name: str, node: ast.AST):
        self.cls = cls
        self.name = name
        self.node = node
        self.jit_names: Dict[str, Set[int]] = {}  # local name -> site idxs
        self.returns: Set[int] = set()            # sites this scope returns
        self.registrar_params: Set[int] = set()   # param idxs it registers


class _ShapeModel:
    """Per-module jit dataflow shared by GS001-GS005 (built once per tree,
    cached on the tree object).

    The fixpoint resolves the repo's real registration idioms: direct
    ``fn = jax.jit(f); note_jit_signature(fn, ...)``; wrapper values
    (``CompiledGraph(jax.jit(run), ...)``); producer methods
    (``self._decode_fn = self._build_decode()`` where the builder returns
    a jit fn, registered later through the self attribute); registrar
    helpers (``self._note_compile(fn, ...)`` passing its param on to
    ``note_jit_signature``); and AOT ``jax.jit(f).lower(a).compile()``
    chains. Blind spot (documented in docs/LINT.md): names are matched
    per-scope and self attributes per-module, so a handle exported to
    ANOTHER module and registered there still reads as unledgered here —
    register (or justify) at the creation module.
    """

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.sites: List[_JitSite] = []
        self.scopes: Dict[Tuple[Optional[str], str], _Scope] = {}
        self.self_jit_attrs: Dict[str, Set[int]] = {}
        self.registered: Set[int] = set()
        # note_jit_signature / ledger.record call spans (GS inventory +
        # the shapetrace runtime-callsite match)
        self.registration_spans: List[Tuple[int, int]] = []
        self._collect_scopes(tree)
        self._fixpoint()

    # -- scope collection -------------------------------------------------
    def _collect_scopes(self, tree: ast.Module) -> None:
        # module level statements form an implicit scope; its bindings
        # (module-level ``fn = jax.jit(...)`` and jit-DECORATED top-level
        # defs) are visible from every other scope in the module
        mod = ast.Module(body=[n for n in tree.body
                               if not isinstance(n, (ast.FunctionDef,
                                                     ast.AsyncFunctionDef,
                                                     ast.ClassDef))],
                         type_ignores=[])
        self.module_scope = _Scope(None, "<module>", mod)
        self.scopes[(None, "<module>")] = self.module_scope

        def add(cls: Optional[str],
                node: "ast.FunctionDef | ast.AsyncFunctionDef") -> None:
            self.scopes[(cls, node.name)] = _Scope(cls, node.name, node)
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    call = dec if isinstance(dec, ast.Call) else None
                    idx = self._site_for(dec, node.name, call)
                    self.module_scope.jit_names.setdefault(
                        node.name, set()).add(idx)

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(None, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        add(node.name, sub)

    # -- site bookkeeping -------------------------------------------------
    def _site_for(self, node: ast.AST, name_hint: str,
                  call: Optional[ast.Call]) -> int:
        line = node.lineno
        for s in self.sites:
            if s.line == line and s.name_hint == name_hint:
                return s.index
        static = _static_arg_names(call) if call is not None else ()
        s = _JitSite(len(self.sites), line, name_hint, static, call)
        self.sites.append(s)
        return s.index

    def _params(self, scope: _Scope) -> List[str]:
        node = scope.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return []
        names = [a.arg for a in node.args.posonlyargs + node.args.args]
        if scope.cls is not None and names and names[0] in ("self", "cls"):
            names = names[1:]
        return names

    # -- jit value resolution ---------------------------------------------
    def jit_value_sites(self, expr: ast.AST, scope: _Scope) -> Set[int]:
        """Site indices the expression's value may carry (creates sites on
        the fly for jit-creating expressions)."""
        if isinstance(expr, ast.Name):
            sites = set(scope.jit_names.get(expr.id, ()))
            if not sites and scope is not self.module_scope:
                sites = set(self.module_scope.jit_names.get(expr.id, ()))
            return sites
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return set(self.self_jit_attrs.get(expr.attr, ()))
            return set()
        if isinstance(expr, ast.IfExp):
            # step_fn = (self._make_a() if cond else self._make_b())
            return (self.jit_value_sites(expr.body, scope)
                    | self.jit_value_sites(expr.orelse, scope))
        if isinstance(expr, ast.BoolOp):
            out: Set[int] = set()
            for v in expr.values:
                out |= self.jit_value_sites(v, scope)
            return out
        if not isinstance(expr, ast.Call):
            return set()
        # jax.jit(f) / pjit(f) — the creation itself
        if _is_direct_jit_call(expr):
            hint = ""
            if expr.args and isinstance(expr.args[0], ast.Name):
                hint = expr.args[0].id
            return {self._site_for(expr, hint, expr)}
        # method chain on a jit value: jax.jit(f).lower(a).compile()
        if isinstance(expr.func, ast.Attribute):
            base = self.jit_value_sites(expr.func.value, scope)
            if base:
                return base
            # producer method: self._build_decode()
            if (isinstance(expr.func.value, ast.Name)
                    and expr.func.value.id == "self"):
                callee = self.scopes.get((scope.cls, expr.func.attr))
                if callee is not None and callee.returns:
                    return set(callee.returns)
        if isinstance(expr.func, ast.Name):
            # producer function: make_step(...)
            callee = self.scopes.get((None, expr.func.id))
            if callee is not None and callee.returns:
                return set(callee.returns)
        # wrapper: CompiledGraph(jax.jit(run), ...) — the wrapper object
        # carries the jit value on to wherever it is registered
        out: Set[int] = set()
        for a in expr.args:
            out |= self.jit_value_sites(a, scope)
        return out

    # -- the fixpoint ------------------------------------------------------
    def _fixpoint(self) -> None:
        for _ in range(10):
            before = (sum(len(v) for s in self.scopes.values()
                          for v in s.jit_names.values()),
                      sum(len(s.returns) for s in self.scopes.values()),
                      sum(len(s.registrar_params)
                          for s in self.scopes.values()),
                      sum(len(v) for v in self.self_jit_attrs.values()),
                      len(self.registered), len(self.sites))
            for scope in self.scopes.values():
                self._scan_scope(scope)
            after = (sum(len(v) for s in self.scopes.values()
                         for v in s.jit_names.values()),
                     sum(len(s.returns) for s in self.scopes.values()),
                     sum(len(s.registrar_params)
                         for s in self.scopes.values()),
                     sum(len(v) for v in self.self_jit_attrs.values()),
                     len(self.registered), len(self.sites))
            if after == before:
                break

    def _scan_scope(self, scope: _Scope) -> None:
        params = self._params(scope)
        for node in ast.walk(scope.node):
            # nested @jax.jit def — binds a jit name in this scope
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        call = dec if isinstance(dec, ast.Call) else None
                        idx = self._site_for(dec, node.name, call)
                        scope.jit_names.setdefault(node.name,
                                                   set()).add(idx)
            elif isinstance(node, ast.Assign):
                sites = self.jit_value_sites(node.value, scope)
                if sites:
                    for tgt in node.targets:
                        self._bind(tgt, sites, scope)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                sites = self.jit_value_sites(node.value, scope)
                if sites:
                    self._bind(node.target, sites, scope)
            elif isinstance(node, ast.Return) and node.value is not None:
                scope.returns |= self.jit_value_sites(node.value, scope)
            elif isinstance(node, ast.Call):
                if _is_direct_jit_call(node):
                    # ensure even unbound creations (``jax.jit(f)(x)``
                    # inline) get a site — GS001 must see them
                    hint = (node.args[0].id if node.args and isinstance(
                        node.args[0], ast.Name) else "")
                    self._site_for(node, hint, node)
                self._scan_registration(node, scope, params)

    def _bind(self, tgt: ast.AST, sites: Set[int], scope: _Scope) -> None:
        if isinstance(tgt, ast.Name):
            scope.jit_names.setdefault(tgt.id, set()).update(sites)
        elif isinstance(tgt, ast.Attribute) and isinstance(
                tgt.value, ast.Name) and tgt.value.id == "self":
            self.self_jit_attrs.setdefault(tgt.attr, set()).update(sites)
        elif isinstance(tgt, ast.Subscript):
            # self._jit_cache[key] = fn — the container carries the value
            self._bind(tgt.value, sites, scope)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind(el, sites, scope)

    def _scan_registration(self, call: ast.Call, scope: _Scope,
                           params: List[str]) -> None:
        tail = _dotted(call.func)
        tail = tail.split(".")[-1] if tail else None
        if tail == "note_jit_signature":
            self.registration_spans.append(
                (call.lineno, getattr(call, "end_lineno", call.lineno)))
            if call.args:
                self._register_arg(call.args[0], scope, params)
            return
        if tail == "record" and any(kw.arg == "cause"
                                    for kw in call.keywords):
            # direct ledger.record(graph=..., cause=...) — a registration
            # site for callsite attribution, but registers no handle
            self.registration_spans.append(
                (call.lineno, getattr(call, "end_lineno", call.lineno)))
            return
        if tail == "export":
            # jax.export.export(jitted) — the AOT export sink
            # (autodiff/export.py): the serialized executable restores
            # through restore_callable, which registers on the ledger
            # with the cache_hit cause, so a jit flowing into export IS
            # ledgered. Only the jax module spellings count
            # (jax.export.export / jexport.export / export.export) — a
            # stray mymod.export() must not launder an unledgered jit.
            parts = (_dotted(call.func) or "").split(".")
            if len(parts) >= 2 and parts[-2] in ("export", "jexport") \
                    and call.args:
                self._register_arg(call.args[0], scope, params)
            return
        # registrar helper: self._note_compile(fn, ...) — the callee
        # passes its param on to note_jit_signature
        callee = None
        if isinstance(call.func, ast.Attribute) and isinstance(
                call.func.value, ast.Name) and call.func.value.id == "self":
            callee = self.scopes.get((scope.cls, call.func.attr))
        elif isinstance(call.func, ast.Name):
            callee = self.scopes.get((None, call.func.id))
        if callee is not None and callee.registrar_params:
            for i in callee.registrar_params:
                if i < len(call.args):
                    self._register_arg(call.args[i], scope, params)

    def _register_arg(self, expr: ast.AST, scope: _Scope,
                      params: List[str]) -> None:
        self.registered |= self.jit_value_sites(expr, scope)
        # is this scope itself a registrar? (its own param flows in)
        if isinstance(expr, ast.Name) and expr.id in params:
            scope.registrar_params.add(params.index(expr.id))

    # -- queries -----------------------------------------------------------
    def unledgered_sites(self) -> List[_JitSite]:
        return [s for s in self.sites if s.index not in self.registered]

    def is_jit_call(self, call: ast.Call, scope: _Scope) -> Set[int]:
        """Sites a call expression dispatches into (``self._decode_fn(...)``
        / ``step_fn(...)``), or empty if it is not a jitted-handle call."""
        if _is_jit_expr(call.func):
            return set()  # the creation, not a dispatch
        return self.jit_value_sites(call.func, scope)


def _static_arg_names(call: Optional[ast.Call]) -> Tuple[str, ...]:
    """Param names covered by static_argnames on a jit call (argnums are
    resolved by GS005 itself, which has the wrapped fn's params)."""
    if call is None:
        return ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals: List[str] = []
            nodes = (kw.value.elts if isinstance(kw.value,
                                                 (ast.Tuple, ast.List))
                     else [kw.value])
            for n in nodes:
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    vals.append(n.value)
            return tuple(vals)
    return ()


def _model(tree: ast.Module, path: str) -> _ShapeModel:
    cached = getattr(tree, "_graftshape_model", None)
    if cached is None:
        cached = _ShapeModel(tree, path)
        tree._graftshape_model = cached
    return cached


# ---------------------------------------------------------------------------
# GS001 — unledgered jit
# ---------------------------------------------------------------------------


def _gs001(model: _ShapeModel, path: str) -> List[Finding]:
    if not _in_library(path):
        return []
    findings: List[Finding] = []
    for site in model.unledgered_sites():
        hint = f" '{site.name_hint}'" if site.name_hint else ""
        findings.append(Finding(
            path=path, line=site.line, rule="GS001", severity="error",
            message=(f"jit callsite{hint} never registered via "
                     f"observe.note_jit_signature — its recompiles would "
                     f"be unattributed in the RecompileLedger (register "
                     f"the returned fn where it is dispatched, or justify "
                     f"why it stays off the ledger)")))
    return sorted(set(findings))


@ast_rule("GS001", "unledgered jit: jax.jit/.lower().compile() callsite "
                   "whose fn is never registered via note_jit_signature — "
                   "recompiles would be unattributed")
def rule_unledgered_jit(tree, lines, path) -> List[Finding]:
    return _apply_justified(_gs001(_model(tree, path), path), lines)


# ---------------------------------------------------------------------------
# GS002 — request-shaped signature
# ---------------------------------------------------------------------------

_SHAPE_SOURCES = {"shape", "size"}
_ARRAY_CTORS = {"zeros", "ones", "empty", "full"}
_BUCKETISH = re.compile(r"(bucket|pad|align)", re.I)


def _refs_self(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Name) and n.id == "self"
               for n in ast.walk(expr))


def _request_tainted_names(fn: ast.AST) -> Set[str]:
    """Names in ``fn`` whose value derives from request/batch EXTENT:
    ``len(x)``, ``x.shape``/``x.size`` of a non-self value, propagated
    through arithmetic. A name laundered through a bucket/pad helper
    (``bucket_len(n)``) is deliberately NOT tainted — that is the
    recognized fix."""
    tainted: Set[str] = set()

    def expr_tainted(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                fname = _dotted(node.func)
                fname = fname.split(".")[-1] if fname else None
                if fname == "len" and node.args \
                        and not _refs_self(node.args[0]):
                    return True
                if fname and _BUCKETISH.search(fname):
                    return False  # bucketed — shape is config-quantized
            if isinstance(node, ast.Attribute) \
                    and node.attr in _SHAPE_SOURCES \
                    and not _refs_self(node.value):
                return True
            if isinstance(node, ast.Name) and node.id in tainted:
                return True
        return False

    for _ in range(4):  # short fixpoint over straight-line propagation
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and expr_tainted(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id not in tainted:
                        tainted.add(tgt.id)
                        grew = True
        if not grew:
            break
    return tainted


def _gs002(model: _ShapeModel, tree: ast.Module,
           path: str) -> List[Finding]:
    if not _in_library(path):
        return []
    findings: List[Finding] = []
    for scope in model.scopes.values():
        tainted = _request_tainted_names(scope.node)
        if not tainted:
            continue
        # names bound to arrays constructed with a tainted extent
        tainted_arrays: Set[str] = set()

        def ctor_tainted(expr: ast.AST) -> bool:
            if not isinstance(expr, ast.Call):
                return False
            fname = _dotted(expr.func)
            fname = fname.split(".")[-1] if fname else None
            if fname not in _ARRAY_CTORS or not expr.args:
                return False
            shape_arg = expr.args[0]
            if _BUCKETISH.search(ast.dump(shape_arg)):
                return False
            return any(isinstance(n, ast.Name) and n.id in tainted
                       for n in ast.walk(shape_arg))

        for node in ast.walk(scope.node):
            if isinstance(node, ast.Assign) and ctor_tainted(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted_arrays.add(tgt.id)

        def arg_request_shaped(arg: ast.AST) -> bool:
            if isinstance(arg, ast.Name) and arg.id in tainted_arrays:
                return True
            if ctor_tainted(arg):
                return True
            # slicing by a non-config variable: ids[:, :n]
            if isinstance(arg, ast.Subscript):
                return any(isinstance(n, ast.Name) and n.id in tainted
                           for n in ast.walk(arg.slice))
            return False

        for node in ast.walk(scope.node):
            if not isinstance(node, ast.Call):
                continue
            if not model.is_jit_call(node, scope):
                continue
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if arg_request_shaped(arg):
                    findings.append(Finding(
                        path=path, line=node.lineno, rule="GS002",
                        severity="error",
                        message=("array argument shaped by request/batch "
                                 "state (len()/.shape dataflow) reaches a "
                                 "jitted fn — every distinct extent is a "
                                 "recompile; pad or bucket the shape to a "
                                 "config-derived size first")))
    return sorted(set(findings))


@ast_rule("GS002", "request-shaped signature: array arg to a jitted fn "
                   "whose shape derives from request/batch state without "
                   "a bucket/pad helper")
def rule_request_shaped(tree, lines, path) -> List[Finding]:
    return _apply_justified(_gs002(_model(tree, path), tree, path), lines)


# ---------------------------------------------------------------------------
# GS003 — traced-value leak
# ---------------------------------------------------------------------------

# attribute reads that are STATIC under trace — they break value taint
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_TAINT_KILLING_CALLS = {"len", "isinstance", "type"}


def _tainted_refs(expr: ast.AST, tainted: Set[str]) -> bool:
    """Does ``expr`` reference a tainted (traced) VALUE? ``x.shape[0]``,
    ``len(x)``, ``x is None`` do not — those are static under trace."""

    def walk(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            fname = fname.split(".")[-1] if fname else None
            if fname in _TAINT_KILLING_CALLS:
                return False
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False  # identity tests (x is None) never concretize
        if isinstance(node, ast.Name):
            return node.id in tainted
        return any(walk(c) for c in ast.iter_child_nodes(node))

    return walk(expr)


def _traced_taint(fn: ast.FunctionDef,
                  static_names: Iterable[str] = ()) -> Set[str]:
    """Param names of a jit-traced fn (minus static args and self),
    propagated through simple assignments."""
    skip = set(static_names) | {"self", "cls"}
    tainted = {a.arg for a in fn.args.posonlyargs + fn.args.args
               if a.arg not in skip}
    tainted |= {a.arg for a in fn.args.kwonlyargs if a.arg not in skip}
    for _ in range(4):
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and _tainted_refs(node.value, tainted):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id not in tainted:
                        tainted.add(tgt.id)
                        grew = True
        if not grew:
            break
    return tainted


def _leaks_in(fn: ast.AST, tainted: Set[str], where: str,
              path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            if _tainted_refs(node.test, tainted):
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(Finding(
                    path=path, line=node.lineno, rule="GS003",
                    severity="error",
                    message=(f"Python `{kind}` on a traced value in "
                             f"{where} — the branch concretizes (or "
                             f"silently bakes in) the tracer; use "
                             f"lax.cond/lax.select or hoist the decision "
                             f"out of the traced path")))
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("int", "float", "bool") \
                and node.args and _tainted_refs(node.args[0], tainted):
            findings.append(Finding(
                path=path, line=node.lineno, rule="GS003",
                severity="error",
                message=(f"{f.id}() on a traced value in {where} forces "
                         f"trace-time concretization — "
                         f"ConcretizationTypeError under jit, or a stale "
                         f"baked-in constant")))
        elif isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args and _tainted_refs(f.value, tainted):
            findings.append(Finding(
                path=path, line=node.lineno, rule="GS003",
                severity="error",
                message=(f".item() on a traced value in {where} blocks "
                         f"on device and fails under trace")))
        elif isinstance(f, ast.Attribute) and f.attr in ("asarray", "array") \
                and _dotted(f.value) in _NUMPY_ALIASES \
                and node.args and _tainted_refs(node.args[0], tainted):
            findings.append(Finding(
                path=path, line=node.lineno, rule="GS003",
                severity="error",
                message=(f"np.{f.attr}() on a traced value in {where} is "
                         f"a host sync / tracer leak; use jnp.{f.attr}")))
    return findings


def _gs003(model: _ShapeModel, tree: ast.Module,
           path: str) -> List[Finding]:
    findings: List[Finding] = []
    module_defs = {n.name: n for n in tree.body
                   if isinstance(n, ast.FunctionDef)}
    for fn in _jit_functions(tree):
        static = set()
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) and _is_jit_expr(dec):
                static |= set(_static_arg_names(dec))
        tainted = _traced_taint(fn, static)
        findings += _leaks_in(fn, tainted,
                              f"jit-traced '{fn.name}'", path)
        # one hop into intra-module helpers the traced body calls with
        # traced arguments — jit-REACHABLE code leaks the same way
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in module_defs
                    and node.func.id != fn.name):
                continue
            helper = module_defs[node.func.id]
            hparams = [a.arg for a in helper.args.posonlyargs
                       + helper.args.args]
            htaint = {hparams[i] for i, a in enumerate(node.args)
                      if i < len(hparams) and _tainted_refs(a, tainted)}
            if htaint:
                findings += _leaks_in(
                    helper, htaint,
                    f"'{helper.name}' (jit-reachable from "
                    f"'{fn.name}')", path)
    return sorted(set(findings))


@ast_rule("GS003", "traced-value leak: int()/float()/bool()/.item()/"
                   "np.asarray() or if/while on traced values inside "
                   "jit-decorated or jit-reachable code")
def rule_traced_leak(tree, lines, path) -> List[Finding]:
    return _apply_justified(_gs003(_model(tree, path), tree, path), lines)


# ---------------------------------------------------------------------------
# GS004 — weak-type churn
# ---------------------------------------------------------------------------

_ARRAYISH_TAILS = {"asarray", "array", "zeros", "ones", "full", "arange"}


def _arg_class(arg: ast.AST) -> Optional[str]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)) \
            and not isinstance(arg.value, bool):
        return "scalar"
    if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub) \
            and isinstance(arg.operand, ast.Constant):
        return "scalar"
    if isinstance(arg, ast.Call):
        fname = _dotted(arg.func)
        if fname and fname.split(".")[-1] in _ARRAYISH_TAILS:
            return "array"
    return None


def _callee_label(func: ast.AST) -> Optional[str]:
    d = _dotted(func)
    return d


def _gs004(model: _ShapeModel, tree: ast.Module,
           path: str) -> List[Finding]:
    if not _in_library(path):
        return []
    # callee label -> arg index -> class -> [lines]
    seen: Dict[Tuple[str, int], Dict[str, List[int]]] = {}
    for scope in model.scopes.values():
        for node in ast.walk(scope.node):
            if not isinstance(node, ast.Call):
                continue
            if not model.is_jit_call(node, scope):
                continue
            label = _callee_label(node.func)
            if label is None:
                continue
            for i, arg in enumerate(node.args):
                cls = _arg_class(arg)
                if cls:
                    seen.setdefault((label, i), {}).setdefault(
                        cls, []).append(node.lineno)
    findings: List[Finding] = []
    for (label, i), classes in sorted(seen.items()):
        if "scalar" in classes and "array" in classes:
            for line in classes["scalar"]:
                findings.append(Finding(
                    path=path, line=line, rule="GS004", severity="error",
                    message=(f"bare Python scalar at positional arg {i} "
                             f"of jitted '{label}' — other call paths "
                             f"pass device arrays there, so the weak-type "
                             f"signature split retraces; wrap with "
                             f"jnp.asarray(..., dtype=...)")))
    return sorted(set(findings))


@ast_rule("GS004", "weak-type churn: bare Python scalar passed "
                   "positionally into a jitted fn where device arrays "
                   "flow on other paths — signature splits on weak types")
def rule_weak_type_churn(tree, lines, path) -> List[Finding]:
    return _apply_justified(_gs004(_model(tree, path), tree, path), lines)


# ---------------------------------------------------------------------------
# GS005 — static-arg hazard
# ---------------------------------------------------------------------------


def _static_coverage(call: ast.Call,
                     wrapped: Optional[ast.FunctionDef]
                     ) -> Tuple[Set[str], Set[int]]:
    """(covered param names, covered positional indices) of a jit call
    with static_argnums/static_argnames."""
    names: Set[str] = set(_static_arg_names(call))
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nodes = (kw.value.elts if isinstance(kw.value,
                                                 (ast.Tuple, ast.List))
                     else [kw.value])
            for n in nodes:
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    if wrapped is not None:
        params = [a.arg for a in wrapped.args.posonlyargs
                  + wrapped.args.args]
        for i in sorted(nums):
            if i < len(params):
                names.add(params[i])
        for nm in names:
            if nm in params:
                nums.add(params.index(nm))
    return names, nums


def _mutated_self_attrs(tree: ast.Module) -> Set[str]:
    """self attributes written OUTSIDE __init__/__new__ — per-call
    mutable state."""
    out: Set[str] = set()
    for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
        for sub in cls.body:
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if sub.name in ("__init__", "__new__"):
                continue
            for node in ast.walk(sub):
                tgt = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and isinstance(
                                t.value, ast.Name) and t.value.id == "self":
                            out.add(t.attr)
                elif isinstance(node, ast.AugAssign) and isinstance(
                        node.target, ast.Attribute) and isinstance(
                        node.target.value, ast.Name) \
                        and node.target.value.id == "self":
                    out.add(node.target.attr)
    return out


def _gs005(model: _ShapeModel, tree: ast.Module,
           path: str) -> List[Finding]:
    if not _in_library(path):
        return []
    mutated = _mutated_self_attrs(tree)
    module_defs: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            module_defs.setdefault(n.name, n)
    findings: List[Finding] = []
    for site in model.sites:
        call = site.call_node
        if call is None or not any(kw.arg in ("static_argnums",
                                              "static_argnames")
                                   for kw in call.keywords):
            continue
        wrapped = module_defs.get(site.name_hint)
        names, nums = _static_coverage(call, wrapped)
        # call sites dispatching this jit handle: a static-covered slot
        # receiving per-call-mutated self state recompiles per mutation
        for scope in model.scopes.values():
            for node in ast.walk(scope.node):
                if not isinstance(node, ast.Call):
                    continue
                if site.index not in model.is_jit_call(node, scope):
                    continue
                hazards: List[Tuple[str, str]] = []
                for i, arg in enumerate(node.args):
                    if i in nums and isinstance(arg, ast.Attribute) \
                            and isinstance(arg.value, ast.Name) \
                            and arg.value.id == "self" \
                            and arg.attr in mutated:
                        hazards.append((f"arg {i}", f"self.{arg.attr}"))
                for kw in node.keywords:
                    if kw.arg in names and isinstance(kw.value,
                                                      ast.Attribute) \
                            and isinstance(kw.value.value, ast.Name) \
                            and kw.value.value.id == "self" \
                            and kw.value.attr in mutated:
                        hazards.append((kw.arg, f"self.{kw.value.attr}"))
                for slot, attr in hazards:
                    findings.append(Finding(
                        path=path, line=node.lineno, rule="GS005",
                        severity="error",
                        message=(f"static arg {slot} of jitted "
                                 f"'{site.name_hint or '<fn>'}' receives "
                                 f"{attr}, which this module mutates "
                                 f"outside __init__ — every new value is "
                                 f"a full recompile; pass it traced or "
                                 f"make it immutable config")))
    return sorted(set(findings))


@ast_rule("GS005", "static-arg hazard: static_argnums/static_argnames "
                   "covering a value the same module mutates per call — "
                   "every mutation recompiles")
def rule_static_arg_hazard(tree, lines, path) -> List[Finding]:
    return _apply_justified(_gs005(_model(tree, path), tree, path), lines)


GS_RULES = ("GS001", "GS002", "GS003", "GS004", "GS005")


# ---------------------------------------------------------------------------
# repo-wide static jit-boundary inventory (the shapetrace cross-validation
# leg — the graftshape analog of rules_concurrency.static_lock_order)
# ---------------------------------------------------------------------------


class ShapeInventory:
    """The statically derived jit-boundary map of the repo:

    * ``jit_sites``: every jit-creating line, with whether its fn is
      ledgered (reaches ``note_jit_signature``) and whether an inline
      ``graftshape: justified`` marker covers it;
    * ``registration_spans``: path -> (start, end) line spans of
      ``note_jit_signature`` / direct ``ledger.record`` calls — the ONLY
      places a ``CompileEvent.callsite`` may legally point at;
    * ``hazards``: path -> raw GS findings (justified ones INCLUDED,
      tagged) — the modules where a ``new_shape`` event is statically
      explicable;
    * ``clean_modules``: paths with zero raw findings — the modules the
      honesty contract says must observe zero ``new_shape``.
    """

    def __init__(self) -> None:
        self.jit_sites: List[Dict[str, object]] = []
        self.registration_spans: Dict[str, List[Tuple[int, int]]] = {}
        self.hazards: Dict[str, List[Dict[str, object]]] = {}
        self.clean_modules: List[str] = []

    def attributes_callsite(self, callsite: str) -> bool:
        """Is a runtime ``path:line`` callsite inside a statically known
        registration span? Line RANGES matter: a multiline
        note_jit_signature call's runtime frame line can be any line of
        the call expression."""
        path, _, line_s = callsite.rpartition(":")
        try:
            line = int(line_s)
        except ValueError:
            return False
        return any(lo <= line <= hi
                   for lo, hi in self.registration_spans.get(path, ()))

    def hazard_module(self, path: str) -> bool:
        return bool(self.hazards.get(path))


def static_shape_inventory(repo_root: str,
                           roots: Sequence[str] = ("deeplearning4j_tpu",)
                           ) -> ShapeInventory:
    """Build the repo-wide jit-boundary inventory for the shapetrace
    runtime cross-validation. Raw findings (pre-justification) feed the
    hazard map — a justified hazard is still a hazard at runtime, just an
    accepted one."""
    inv = ShapeInventory()
    for rel in iter_py_files(roots, repo_root):
        with open(os.path.join(repo_root, rel), "r",
                  encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError:
            continue
        lines = src.splitlines()
        just = _justified_lines(lines)

        def justified(rule: str, line: int) -> bool:
            return (rule in just.get(line, ())
                    or rule in just.get(line - 1, ()))

        model = _model(tree, rel)
        raw: List[Finding] = []
        raw += _gs001(model, rel)
        raw += _gs002(model, tree, rel)
        raw += _gs003(model, tree, rel)
        raw += _gs004(model, tree, rel)
        raw += _gs005(model, tree, rel)
        if model.registration_spans:
            inv.registration_spans[rel] = sorted(model.registration_spans)
        for site in model.sites:
            inv.jit_sites.append({
                "path": rel, "line": site.line,
                "name": site.name_hint,
                "ledgered": site.index in model.registered,
                "justified": justified("GS001", site.line),
            })
        if raw:
            inv.hazards[rel] = [
                {"line": f.line, "rule": f.rule,
                 "justified": justified(f.rule, f.line)}
                for f in sorted(set(raw))]
        else:
            inv.clean_modules.append(rel)
    inv.clean_modules.sort()
    return inv
