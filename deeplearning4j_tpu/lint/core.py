"""graftlint core: finding model, file walker, suppression, baseline diff.

The analysis itself lives in ``rules_ast`` (pure-AST rules, no jax import)
and ``rules_consistency`` (rules that load the live registries). This module
is deliberately dependency-free so fixture-level unit tests can lint source
snippets without touching a backend.

Baseline contract (the "grandfather" mechanism — VERDICT round 5, items 4/8):
``lint_baseline.json`` maps a *stable key* (rule|path|message — no line
numbers, so unrelated edits don't invalidate entries) to the number of
grandfathered occurrences. The suite fails only on findings **above** the
baselined count; entries whose count has dropped are reported as *fixed* so
the baseline can shrink (``--write-baseline`` regenerates it).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")

# directories never walked (build trees, caches, VCS)
_SKIP_DIRS = {"__pycache__", ".git", "build", ".pytest_cache", "node_modules",
              ".claude"}

_DISABLE_RE = re.compile(r"graftlint:\s*disable(?:=([A-Z0-9, ]+))?")
_SKIP_FILE_RE = re.compile(r"graftlint:\s*skip-file")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint hit. ``key`` excludes the line number on purpose: baseline
    entries must survive unrelated edits above the flagged line."""

    path: str          # repo-relative, forward slashes
    line: int
    rule: str          # e.g. "GL001"
    severity: str      # "error" | "warning"
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.severity}: {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "severity": self.severity, "message": self.message}


# rule id -> (callable(tree, lines, path) -> findings, one-line description)
AST_RULES: Dict[str, Tuple[Callable[..., List[Finding]], str]] = {}


def ast_rule(rule_id: str, description: str):
    """Decorator registering a pure-AST rule."""

    def wrap(fn):
        AST_RULES[rule_id] = (fn, description)
        fn.rule_id = rule_id
        fn.description = description
        return fn

    return wrap


def _suppressed_lines(lines: Sequence[str]) -> Dict[int, Optional[set]]:
    """Map 1-based line -> set of suppressed rule ids (None = all rules)."""
    out: Dict[int, Optional[set]] = {}
    for i, text in enumerate(lines, start=1):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        if m.group(1):
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        else:
            out[i] = None
    return out


def lint_source(src: str, path: str = "<fixture>",
                rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the AST rules over one source string. The fixture-test entry
    point; also the per-file worker for :func:`lint_paths`."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [Finding(path=path, line=exc.lineno or 1, rule="GL000",
                        severity="error",
                        message=f"syntax error: {exc.msg}")]
    lines = src.splitlines()
    head = "\n".join(lines[:5])
    if _SKIP_FILE_RE.search(head):
        return []
    suppressed = _suppressed_lines(lines)
    wanted = set(rules) if rules is not None else None
    findings: List[Finding] = []
    for rule_id, (fn, _desc) in sorted(AST_RULES.items()):
        if wanted is not None and rule_id not in wanted:
            continue
        for f in fn(tree, lines, path):
            sup = suppressed.get(f.line, ())
            if sup is None or (sup and f.rule in sup):
                continue
            findings.append(f)
    return sorted(findings)


def iter_py_files(roots: Sequence[str], repo_root: str) -> List[str]:
    """Repo-relative paths of every .py file under ``roots`` (files or
    directories), deterministic order."""
    out: List[str] = []
    for root in roots:
        absroot = os.path.join(repo_root, root)
        if os.path.isfile(absroot) and root.endswith(".py"):
            # normalize like the directory branch: Finding.path must be
            # repo-relative or baseline keys never match
            out.append(os.path.relpath(absroot, repo_root).replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(absroot):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), repo_root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def lint_paths(roots: Sequence[str], repo_root: str,
               rules: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for rel in iter_py_files(roots, repo_root):
        with open(os.path.join(repo_root, rel), "r", encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(lint_source(src, path=rel, rules=rules))
    return sorted(findings)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


_DEFAULT_BASELINE_COMMENT = (
    "graftlint grandfathered findings — every entry is debt; "
    "shrink, never grow. Regenerate: make lint-baseline")


def write_baseline(path: str, findings: Sequence[Finding],
                   allow_growth: bool = False,
                   comment: str = _DEFAULT_BASELINE_COMMENT
                   ) -> Dict[str, int]:
    """Write the baseline; shrink-only by default. Findings whose key is
    absent from (or whose count exceeds) the EXISTING baseline are refused
    — returned to the caller instead of written — so regenerating the
    baseline can never silently grandfather a regression. ``allow_growth``
    is the explicit escape hatch for onboarding a brand-new rule.
    ``comment``: the self-describing header (graftcheck passes its own —
    this module is the shared Finding/baseline plumbing for both tools)."""
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    refused: Dict[str, int] = {}
    if not allow_growth and os.path.exists(path):
        old = load_baseline(path)
        for key in sorted(counts):
            allowed = old.get(key, 0)
            if counts[key] > allowed:
                refused[key] = counts[key] - allowed
                if allowed:
                    counts[key] = allowed
                else:
                    del counts[key]
    payload = {
        "comment": comment,
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    # atomic: the gate reads this file — a torn baseline would make every
    # finding look new, so write tmp + os.replace
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
    return refused


def run_baselined_cli(tool: str, findings: Sequence[Finding],
                      baseline_path: str, *, write: bool,
                      allow_growth: bool, json_mode: bool,
                      comment: str = _DEFAULT_BASELINE_COMMENT,
                      suppress_fixed: bool = False,
                      fail_hint: str = "") -> int:
    """Shared CLI tail for the baselined analysis tools (graftlint /
    graftcheck): --write-baseline (shrink-only, refusal reporting), or
    diff-and-report with the one-JSON-line gate contract. Returns the
    process exit code. ``suppress_fixed``: a subset scan cannot tell
    "fixed" from "outside the scanned paths" — report none."""
    if write:
        refused = write_baseline(baseline_path, findings,
                                 allow_growth=allow_growth, comment=comment)
        kept = len(findings) - sum(refused.values())
        if json_mode:   # keep the one-JSON-line contract in every mode
            print(json.dumps({"tool": tool, "wrote_baseline": True,
                              "total": kept,
                              "refused_growth": sum(refused.values()),
                              "baseline_path": baseline_path},
                             sort_keys=True))
        else:
            print(f"{tool}: wrote {kept} grandfathered findings "
                  f"to {baseline_path}")
            for key, n in sorted(refused.items()):
                print(f"{tool}: REFUSED to grandfather new finding "
                      f"(x{n}): {key}")
            if refused:
                print(f"{tool}: fix the refused findings (or, only when "
                      f"onboarding a new rule, re-run with --allow-growth)")
        return 1 if refused else 0

    baseline = load_baseline(baseline_path)
    new, fixed = diff_baseline(findings, baseline)
    if suppress_fixed:
        fixed = []

    if json_mode:
        # ONE parsable line — the gate/driver artifact contract
        print(json.dumps({
            "tool": tool,
            "total": len(findings),
            "baselined": len(findings) - len(new),
            "new": len(new),
            "fixed_baseline_keys": len(fixed),
            "findings": [f.as_dict() for f in new[:50]],
        }, sort_keys=True))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if fixed:
        print(f"{tool}: {len(fixed)} baseline entr"
              f"{'y is' if len(fixed) == 1 else 'ies are'} fixed — run "
              f"--write-baseline to shrink the baseline")
    print(f"{tool}: {len(findings)} findings "
          f"({len(findings) - len(new)} grandfathered, {len(new)} new)")
    if new:
        print(f"{tool}: FAIL — {fail_hint or 'fix the new findings above'}")
        return 1
    return 0


def diff_baseline(findings: Sequence[Finding], baseline: Dict[str, int]
                  ) -> Tuple[List[Finding], List[str]]:
    """Return (new findings beyond the grandfathered counts, baseline keys
    now fully or partially fixed)."""
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    new: List[Finding] = []
    for key, fs in by_key.items():
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            # report the excess occurrences (latest lines first is arbitrary;
            # keep source order for readability)
            new.extend(sorted(fs)[allowed:])
    fixed = sorted(k for k, n in baseline.items()
                   if len(by_key.get(k, ())) < n)
    return sorted(new), fixed
