"""graftlock — static lock-discipline rules for the threaded serving stack.

GL011  lock-order inversion: a cycle in the module's lock-order graph
       (built from ``with <lock>:`` nesting plus the intra-module call
       graph) is a potential deadlock
GL012  inconsistently-guarded shared state: an attribute *mostly* accessed
       under its class's lock is touched without it on a path reachable
       from a thread entry point; also read-modify-write (``self.x += 1``)
       on a thread-entry path outside any lock in a lock-owning class
GL013  blocking call while holding a lock: ``.join()``, queue ``.get()`` /
       ``future.result()`` / ``.wait()`` without a timeout, ``time.sleep``,
       ``jax.device_get`` / ``.block_until_ready()`` inside a lock body
GL014  external callback invoked under a held lock: ``set_result`` /
       ``set_exception`` / ``add_done_callback`` and listener/``on_*``/
       hook calls run arbitrary foreign code while the lock is held —
       the cluster-migration re-entrancy hazard

Same house rules as ``rules_ast``: deliberately conservative (a static
pass that cries wolf gets deleted from the gate), blind spots documented
in docs/LINT.md. A true positive the code *means* is suppressed inline
with ``# graftlock: justified(GL01x): <reason>`` — the reason is
mandatory; a bare marker does not suppress.

Beyond the per-file rules this module exports the repo-wide static
lock-order graph (:func:`static_lock_order`) that the runtime shadow-lock
tracer (``testing/locktrace.py``) cross-validates: every lock-order edge
actually observed under the threaded test suites must already be an edge
here, and the combined graph must stay acyclic.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.lint.core import Finding, ast_rule, iter_py_files

# ---------------------------------------------------------------------------
# inline justification (the graftlock analog of "graftlint: disable")
# ---------------------------------------------------------------------------

_JUSTIFIED_RE = re.compile(
    r"graftlock:\s*justified\((GL\d{3})\)\s*:\s*(\S.*)")


def _justified_lines(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """1-based line -> rule ids justified there. Only matches carrying a
    nonempty written reason suppress — acceptance requires every justified
    site to say WHY."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, start=1):
        for m in _JUSTIFIED_RE.finditer(text):
            out.setdefault(i, set()).add(m.group(1))
    return out


def _apply_justified(findings: List[Finding],
                     lines: Sequence[str]) -> List[Finding]:
    """A justification suppresses a finding on its own line or on the
    line directly below (comment-above form, for statements too long to
    carry a trailing comment)."""
    just = _justified_lines(lines)
    return [f for f in findings
            if f.rule not in just.get(f.line, ())
            and f.rule not in just.get(f.line - 1, ())]


# ---------------------------------------------------------------------------
# lock model: which attributes ARE locks, and what a method acquires
# ---------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

# method names that run on a worker thread even without an explicit
# Thread(target=...) in the same class (the codebase's worker idioms)
_ENTRY_NAMES = {"run", "_run", "_serve_loop", "_worker", "_worker_loop"}


def _is_lock_ctor(node: ast.AST) -> bool:
    """True for ``threading.Lock()`` / ``Lock()`` / ``threading.Condition(
    ...)`` — the expression creates a lock-like object."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    return name in _LOCK_CTORS


def _self_attr(node: ast.AST) -> Optional[str]:
    """'_lock' for ``self._lock``, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Method:
    """One function/method with its lock acquisitions and call sites."""

    def __init__(self, cls: Optional[str], name: str, node: ast.AST):
        self.cls = cls
        self.name = name
        self.node = node
        # direct acquisitions: (lock node name, with-stmt line)
        self.acquires: List[Tuple[str, int]] = []
        # edges (held -> acquired, line) from literal with-nesting
        self.nest_edges: List[Tuple[str, str, int]] = []
        # call sites: (callee name, line, held locks at the call)
        self.calls: List[Tuple[str, int, Tuple[str, ...]]] = []
        # every statement line range inside a held-lock body, with the
        # lock name — GL013/GL014 scan these
        self.lock_bodies: List[Tuple[str, ast.With, int]] = []


class _ModuleModel:
    """Per-module lock/call model shared by GL011-GL014 (built once per
    tree, cached on the tree object)."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        # class -> {lock attr}
        self.class_locks: Dict[str, Set[str]] = {}
        # lock attr -> {classes defining it} (module-wide, for other.X)
        self.attr_owners: Dict[str, Set[str]] = {}
        # (cls or None, method name) -> _Method
        self.methods: Dict[Tuple[Optional[str], str], _Method] = {}
        # class -> thread-entry method names
        self.entries: Dict[str, Set[str]] = {}
        self._collect_locks(tree)
        self._collect_methods(tree)
        self._collect_entries(tree)

    # -- pass 1: find every ``self.X = threading.Lock()``-style definition
    def _collect_locks(self, tree: ast.Module) -> None:
        for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
            locks: Set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr:
                            locks.add(attr)
                elif (isinstance(node, ast.AnnAssign) and node.value is not None
                        and _is_lock_ctor(node.value)):
                    attr = _self_attr(node.target)
                    if attr:
                        locks.add(attr)
            if locks:
                self.class_locks[cls.name] = locks
                for attr in locks:
                    self.attr_owners.setdefault(attr, set()).add(cls.name)

    # -- naming: a lock expression -> stable node name ("Cls.attr")
    def lock_name(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None:
            if cls and attr in self.class_locks.get(cls, ()):
                return f"{cls}.{attr}"
            return None
        if isinstance(expr, ast.Attribute):  # other.X — resolve by attr
            owners = self.attr_owners.get(expr.attr, set())
            if len(owners) == 1:
                return f"{next(iter(owners))}.{expr.attr}"
            if owners:
                return f"?.{expr.attr}"
        return None

    # -- pass 2: per-method acquisitions, nesting edges, call sites
    def _collect_methods(self, tree: ast.Module) -> None:
        def visit_fn(fn, cls: Optional[str]) -> None:
            m = _Method(cls, fn.name, fn)
            self.methods[(cls, fn.name)] = m

            def walk(node, held: Tuple[str, ...]) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue  # nested defs get their own _Method? no —
                        # closures run later, outside the held context
                    if isinstance(child, ast.With):
                        names = []
                        for item in child.items:
                            nm = self.lock_name(item.context_expr, cls)
                            if nm is None and isinstance(
                                    item.context_expr, ast.Call):
                                # ``with self._cv:`` never calls; a Call
                                # (e.g. ``with open(...)``) is not a lock
                                nm = None
                            if nm:
                                m.acquires.append((nm, child.lineno))
                                for h in held:
                                    if h != nm:
                                        m.nest_edges.append(
                                            (h, nm, child.lineno))
                                names.append(nm)
                        if names:
                            m.lock_bodies.append(
                                (names[-1], child, child.lineno))
                        walk(child, held + tuple(names))
                        continue
                    if isinstance(child, ast.Call):
                        callee = None
                        f = child.func
                        if isinstance(f, ast.Name):
                            callee = f.id
                        elif isinstance(f, ast.Attribute):
                            callee = f.attr
                        if callee:
                            m.calls.append((callee, child.lineno, held))
                    walk(child, held)

            walk(fn, ())

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        visit_fn(sub, node.name)

    # -- pass 3: thread entry points
    def _collect_entries(self, tree: ast.Module) -> None:
        for cls in self.class_locks:
            self.entries[cls] = set()
        for node in ast.walk(tree):
            # threading.Thread(target=self.X) — X runs on a worker thread
            if isinstance(node, ast.Call):
                fn = node.func
                fname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if fname == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            attr = _self_attr(kw.value)
                            if attr:
                                for cls in self._classes_with_method(attr):
                                    self.entries.setdefault(cls,
                                                            set()).add(attr)
                # f.add_done_callback(self.X) / reg.add_listener(self.X):
                # X runs on whatever thread completes/fires
                if fname in ("add_done_callback", "add_listener",
                             "register_callback"):
                    for arg in node.args:
                        attr = _self_attr(arg)
                        if attr:
                            for cls in self._classes_with_method(attr):
                                self.entries.setdefault(cls, set()).add(attr)
            # obj.on_death = self.X (or a lambda closing over self.X) —
            # registered callback, runs on a foreign thread
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr.startswith("on_")):
                        for attr in self._callback_targets(node.value):
                            for cls in self._classes_with_method(attr):
                                self.entries.setdefault(cls, set()).add(attr)
        for (cls, name) in self.methods:
            if cls and name in _ENTRY_NAMES:
                self.entries.setdefault(cls, set()).add(name)

    @staticmethod
    def _callback_targets(value: ast.AST) -> List[str]:
        """Method names a registered-callback expression hands out:
        ``self.X`` directly, or any ``self.X(...)`` a wrapping lambda
        calls."""
        attr = _self_attr(value)
        if attr:
            return [attr]
        if isinstance(value, ast.Lambda):
            out = []
            for node in ast.walk(value.body):
                if isinstance(node, ast.Call):
                    a = _self_attr(node.func)
                    if a:
                        out.append(a)
            return out
        return []

    def _classes_with_method(self, name: str) -> List[str]:
        return [c for (c, n) in self.methods if c is not None and n == name]

    # -- intra-class reachability from the thread entry points
    def entry_reachable(self, cls: str) -> Set[str]:
        """Method names of ``cls`` reachable (intra-class call graph) from
        its thread entry points."""
        seen: Set[str] = set()
        todo = list(self.entries.get(cls, ()))
        while todo:
            name = todo.pop()
            if name in seen:
                continue
            seen.add(name)
            m = self.methods.get((cls, name))
            if m is None:
                continue
            for callee, _line, _held in m.calls:
                if (cls, callee) in self.methods and callee not in seen:
                    todo.append(callee)
        return seen

    # -- transitive lock acquisitions per method (intra-module fixpoint)
    def transitive_acquires(self) -> Dict[Tuple[Optional[str], str],
                                          Set[str]]:
        acq = {key: {a for a, _ in m.acquires}
               for key, m in self.methods.items()}
        changed = True
        while changed:
            changed = False
            for key, m in self.methods.items():
                for callee, _line, _held in m.calls:
                    for ckey in ((m.cls, callee), (None, callee)):
                        if ckey in acq and not acq[ckey] <= acq[key]:
                            acq[key] |= acq[ckey]
                            changed = True
        return acq

    # -- the module's lock-order graph: (a, b, line, via) edges
    def lock_edges(self) -> List[Tuple[str, str, int, str]]:
        edges: List[Tuple[str, str, int, str]] = []
        trans = self.transitive_acquires()
        for key, m in self.methods.items():
            where = f"{m.cls}.{m.name}" if m.cls else m.name
            for a, b, line in m.nest_edges:
                edges.append((a, b, line, where))
            for callee, line, held in m.calls:
                if not held:
                    continue
                for ckey in ((m.cls, callee), (None, callee)):
                    for b in trans.get(ckey, ()):
                        for a in held:
                            if a != b:
                                edges.append(
                                    (a, b, line, f"{where} -> {callee}"))
        return edges


def _model(tree: ast.Module, path: str) -> _ModuleModel:
    cached = getattr(tree, "_graftlock_model", None)
    if cached is None:
        cached = _ModuleModel(tree, path)
        tree._graftlock_model = cached
    return cached


def _find_cycle(edges: Iterable[Tuple[str, str]]
                ) -> Optional[List[str]]:
    """One cycle as a node list [a, b, ..., a], or None if acyclic."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        stack.append(n)
        for nb in sorted(graph[n]):
            if color[nb] == GREY:
                return stack[stack.index(nb):] + [nb]
            if color[nb] == WHITE:
                cyc = dfs(nb)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


# ---------------------------------------------------------------------------
# GL011 — lock-order inversion
# ---------------------------------------------------------------------------


@ast_rule("GL011", "lock-order inversion: cycle in the module's lock-order "
                   "graph (with-nesting + intra-module calls) — potential "
                   "deadlock")
def rule_lock_order(tree, lines, path) -> List[Finding]:
    model = _model(tree, path)
    edges = model.lock_edges()
    findings: List[Finding] = []
    cyc = _find_cycle({(a, b) for a, b, _l, _w in edges})
    if cyc:
        # name both acquisition paths: for each edge of the cycle, the
        # earliest site establishing it
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            best = min((e for e in edges if e[0] == a and e[1] == b),
                       key=lambda e: e[2])
            sites.append(f"{a}->{b} at line {best[2]} ({best[3]})")
        findings.append(Finding(
            path=path, line=min(e[2] for e in edges
                                if (e[0], e[1]) in set(zip(cyc, cyc[1:]))),
            rule="GL011", severity="error",
            message=("lock-order cycle " + " -> ".join(cyc)
                     + "; acquisition paths: " + "; ".join(sites)
                     + " — two threads taking these in opposite order "
                       "deadlock")))
    return _apply_justified(findings, lines)


# ---------------------------------------------------------------------------
# GL012 — inconsistently-guarded shared state
# ---------------------------------------------------------------------------


class _AttrAccess:
    __slots__ = ("attr", "line", "store", "guarded", "method", "augmented")

    def __init__(self, attr, line, store, guarded, method, augmented):
        self.attr = attr
        self.line = line
        self.store = store
        self.guarded = guarded
        self.method = method
        self.augmented = augmented


def _locked_only_methods(model: _ModuleModel, cls: str) -> Set[str]:
    """Methods of ``cls`` that are ONLY ever called with a class lock
    already held (the ``_health_check``-from-``_routable`` /
    ``*_locked`` helper convention): every intra-class call site carries
    a held lock of this class, and there is at least one call site.
    Their accesses count as guarded. Blind spot: call sites in OTHER
    modules are invisible — a cross-module unlocked caller defeats
    this."""
    locks = {f"{cls}.{a}" for a in model.class_locks.get(cls, ())}
    # callee -> [(caller, lock held at the call site)]
    sites: Dict[str, List[Tuple[str, bool]]] = {}
    for (c, name), m in model.methods.items():
        if c != cls:
            continue
        for callee, _line, held in m.calls:
            if (cls, callee) in model.methods:
                sites.setdefault(callee, []).append(
                    (name, bool(set(held) & locks)))
    out: Set[str] = set()
    changed = True
    while changed:  # fixpoint: a locked-only caller's sites count as held
        changed = False
        for callee, ss in sites.items():
            if callee not in out and all(held or caller in out
                                         for caller, held in ss):
                out.add(callee)
                changed = True
    return out


def _class_attr_accesses(model: _ModuleModel, tree: ast.Module,
                         cls_node: ast.ClassDef) -> List[_AttrAccess]:
    """Every ``self.X`` load/store in the class's methods, tagged with
    whether a lock of THIS class was held (literal with-nesting, or the
    method is only ever called under the lock) at the access.
    ``__init__``/``__del__`` are construction/teardown — single-threaded
    by contract, excluded entirely."""
    cls = cls_node.name
    locks = model.class_locks.get(cls, set())
    locked_only = _locked_only_methods(model, cls)
    out: List[_AttrAccess] = []
    for sub in cls_node.body:
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if sub.name in ("__init__", "__new__", "__del__"):
            continue

        def walk(node, held: bool, method=sub.name) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                now_held = held
                if isinstance(child, ast.With):
                    for item in child.items:
                        attr = _self_attr(item.context_expr)
                        if attr in locks:
                            now_held = True
                if isinstance(child, ast.AugAssign):
                    attr = _self_attr(child.target)
                    if attr is not None and attr not in locks:
                        out.append(_AttrAccess(attr, child.lineno, True,
                                               now_held, method, True))
                elif isinstance(child, ast.Attribute):
                    attr = _self_attr(child)
                    if attr is not None and attr not in locks:
                        out.append(_AttrAccess(
                            attr, child.lineno,
                            isinstance(child.ctx, (ast.Store, ast.Del)),
                            now_held, method, False))
                walk(child, now_held, method)

        walk(sub, sub.name in locked_only)
    return out


@ast_rule("GL012", "inconsistently-guarded shared state: attribute mostly "
                   "accessed under the class lock touched without it on a "
                   "thread-entry path (or read-modify-write off-lock)")
def rule_guarded_state(tree, lines, path) -> List[Finding]:
    model = _model(tree, path)
    findings: List[Finding] = []
    for cls_node in (n for n in ast.walk(tree)
                     if isinstance(n, ast.ClassDef)):
        cls = cls_node.name
        if cls not in model.class_locks:
            continue
        if not model.entries.get(cls):
            continue  # no thread ever enters this class — no data race
        reachable = model.entry_reachable(cls)
        accesses = _class_attr_accesses(model, tree, cls_node)
        by_attr: Dict[str, List[_AttrAccess]] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            guarded = [a for a in accs if a.guarded]
            unguarded = [a for a in accs if not a.guarded]
            # arm (a): "mostly guarded" inference — private attrs only;
            # >= 2 guarded accesses and a guarded majority make the lock
            # the attribute's de-facto owner
            if (attr.startswith("_") and len(guarded) >= 2
                    and len(guarded) > len(unguarded)):
                for a in unguarded:
                    if a.method in reachable or any(
                            g.method in reachable for g in guarded):
                        findings.append(Finding(
                            path=path, line=a.line, rule="GL012",
                            severity="error",
                            message=(f"{cls}.{attr} is lock-guarded at "
                                     f"{len(guarded)} sites but "
                                     f"{'written' if a.store else 'read'} "
                                     f"without the lock in {a.method}() — "
                                     f"racy against the guarded accesses")))
            # arm (b): read-modify-write on a worker-thread path with no
            # lock held — a lost update even when no access is guarded
            for a in accs:
                if (a.augmented and not a.guarded
                        and a.method in reachable):
                    findings.append(Finding(
                        path=path, line=a.line, rule="GL012",
                        severity="error",
                        message=(f"{cls}.{attr} += ... in {a.method}() runs "
                                 f"on a thread-entry path without the class "
                                 f"lock — concurrent increments lose "
                                 f"updates")))
    return _apply_justified(sorted(set(findings)), lines)


# ---------------------------------------------------------------------------
# GL013 — blocking call while holding a lock
# ---------------------------------------------------------------------------

def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _walk_no_defs(node: ast.AST):
    """ast.walk that does NOT descend into nested function/lambda bodies —
    a closure defined under a lock runs later, without it."""
    todo = [node]
    while todo:
        n = todo.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            todo.append(child)


def _dotted_tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


_QUEUEISH = re.compile(r"(^q$|queue|_q$)", re.I)


@ast_rule("GL013", "blocking call while holding a lock: join/get/result/"
                   "wait without timeout, time.sleep, device_get — every "
                   "other waiter stalls behind it")
def rule_blocking_under_lock(tree, lines, path) -> List[Finding]:
    model = _model(tree, path)
    findings: List[Finding] = []
    for key, m in model.methods.items():
        for lock_name, with_node, _line in m.lock_bodies:
            lock_attr = lock_name.split(".")[-1]
            for node in _walk_no_defs(with_node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                fname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                dotted = None
                if isinstance(fn, ast.Attribute):
                    dotted = _dotted_tail(fn.value)
                msg = None
                if fname == "sleep" and dotted in (None, "time"):
                    msg = "time.sleep holds the lock for the whole nap"
                elif fname == "join" and not node.args and \
                        not _has_timeout(node):
                    msg = (".join() with no timeout can wait forever "
                           "while the lock starves every other thread")
                elif fname == "device_get" or fname == "block_until_ready":
                    msg = (f".{fname}() synchronizes with the device "
                           f"under the lock — host threads stall for "
                           f"device time")
                elif fname in ("get", "result", "wait") and \
                        not node.args and not _has_timeout(node):
                    recv = dotted
                    if fname == "wait" and recv == lock_attr:
                        continue  # Condition.wait on the HELD lock
                        # releases it — the CV pattern, not a block
                    if fname == "get" and not (
                            recv and _QUEUEISH.search(recv)):
                        continue  # dict.get() noise — only queue-ish
                        # receivers are credible blockers
                    msg = (f".{fname}() without a timeout blocks "
                           f"indefinitely while holding the lock")
                if msg:
                    findings.append(Finding(
                        path=path, line=node.lineno, rule="GL013",
                        severity="error",
                        message=f"blocking call under {lock_name}: {msg}"))
    return _apply_justified(sorted(set(findings)), lines)


# ---------------------------------------------------------------------------
# GL014 — external callback invoked under a held lock
# ---------------------------------------------------------------------------

_CB_NAME = re.compile(r"(^on_[a-z0-9_]+$|callback|listener|^hook$|_cb$|"
                      r"_hook$)", re.I)
_FUTURE_COMPLETERS = {"set_result", "set_exception", "add_done_callback"}


def _callback_calls(m: _Method, with_node: ast.With
                    ) -> List[Tuple[int, str]]:
    """(line, description) for every direct callback invocation inside
    ``with_node``'s body."""
    out: List[Tuple[int, str]] = []
    for node in _walk_no_defs(with_node):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if fname is None:
            continue
        if fname in _FUTURE_COMPLETERS:
            out.append((node.lineno,
                        f"{fname}() runs the future's done-callbacks "
                        f"synchronously on this thread"))
        elif _CB_NAME.search(fname):
            out.append((node.lineno,
                        f"{fname}() is a listener/callback — foreign code "
                        f"runs while the lock is held"))
    return out


@ast_rule("GL014", "external callback (listener/on_*/set_result/"
                   "add_done_callback) invoked while a lock is held — "
                   "re-entrancy and cross-lock deadlock hazard")
def rule_callback_under_lock(tree, lines, path) -> List[Finding]:
    model = _model(tree, path)
    findings: List[Finding] = []
    # which methods invoke callbacks OUTSIDE any of their own lock bodies
    # (so a locked caller inherits the hazard through the call)
    cb_methods: Dict[Tuple[Optional[str], str], List[Tuple[int, str]]] = {}
    for key, m in model.methods.items():
        in_lock_lines: Set[int] = set()
        for _nm, wnode, _l in m.lock_bodies:
            for n in ast.walk(wnode):
                ln = getattr(n, "lineno", None)
                if ln is not None:
                    in_lock_lines.add(ln)
        hits = []
        for node in _walk_no_defs(m.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if fname and (fname in _FUTURE_COMPLETERS
                          or _CB_NAME.search(fname)) \
                    and node.lineno not in in_lock_lines:
                hits.append((node.lineno, fname))
        if hits:
            cb_methods[key] = hits

    for key, m in model.methods.items():
        for lock_name, with_node, _line in m.lock_bodies:
            for line, desc in _callback_calls(m, with_node):
                findings.append(Finding(
                    path=path, line=line, rule="GL014", severity="error",
                    message=f"callback under {lock_name}: {desc}"))
            # call-graph propagation: a call under the lock into a method
            # that completes futures / fires listeners
            for node in _walk_no_defs(with_node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                callee = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if callee is None:
                    continue
                for ckey in ((m.cls, callee), (None, callee)):
                    if ckey in cb_methods and ckey != key:
                        cl, cn = cb_methods[ckey][0]
                        findings.append(Finding(
                            path=path, line=node.lineno, rule="GL014",
                            severity="error",
                            message=(f"callback under {lock_name}: "
                                     f"{callee}() reaches {cn}() at line "
                                     f"{cl} — foreign code runs while the "
                                     f"lock is held")))
    return _apply_justified(sorted(set(findings)), lines)


# ---------------------------------------------------------------------------
# repo-wide static lock-order graph (the locktrace cross-validation leg)
# ---------------------------------------------------------------------------


class LockGraph:
    """Union of the per-module lock-order graphs with cross-module call
    propagation: node names are ``Class.attr``; an edge a->b means "some
    path acquires b while holding a"."""

    def __init__(self):
        self.edges: Set[Tuple[str, str]] = set()
        self.sites: Dict[Tuple[str, str], str] = {}
        self.nodes: Set[str] = set()

    def add(self, a: str, b: str, site: str) -> None:
        if a == b or a.startswith("?.") or b.startswith("?."):
            return  # self-edges are RLock re-entry; unresolved owners
            # ("?.attr") would alias distinct locks into false edges
        self.edges.add((a, b))
        self.sites.setdefault((a, b), site)
        self.nodes.update((a, b))

    def cycle(self) -> Optional[List[str]]:
        return _find_cycle(self.edges)

    def closure(self) -> Set[Tuple[str, str]]:
        """Transitive closure — the runtime tracer records an edge for
        EVERY held lock at each acquisition, so held-through-two-levels
        shows up as the composed edge."""
        reach: Dict[str, Set[str]] = {n: set() for n in self.nodes}
        for a, b in self.edges:
            reach[a].add(b)
        changed = True
        while changed:
            changed = False
            for a in reach:
                new = set()
                for b in reach[a]:
                    new |= reach.get(b, set())
                if not new <= reach[a]:
                    reach[a] |= new
                    changed = True
        return {(a, b) for a, bs in reach.items() for b in bs}


# names the cross-module propagation must NOT resolve by-name: they are
# methods of builtin containers / threading primitives (or builtins), so
# `self.events.clear()` would otherwise alias into SOME class's `clear`
# and fabricate lock edges (observed: SpanTracer.clear -> list.clear
# matched RadixPrefixCache.clear, closing a false deadlock cycle)
_GENERIC_CALLEES: Set[str] = (
    set(dir(list)) | set(dir(dict)) | set(dir(set)) | set(dir(str))
    | set(dir(bytes)) | {"min", "max", "sum", "len", "abs", "sorted",
                         "start", "run", "join", "is_alive",
                         "acquire", "release", "wait", "notify",
                         "notify_all", "locked", "popleft", "appendleft"})


def static_lock_order(repo_root: str,
                      roots: Sequence[str] = ("deeplearning4j_tpu",)
                      ) -> LockGraph:
    """Build the repo-wide lock-order graph. Per-module edges come from
    :meth:`_ModuleModel.lock_edges`; cross-module edges from calls made
    while holding a lock into a method NAME that any indexed class
    defines (union over owners when ambiguous — over-approximation is
    the safe direction for a graph whose job is to stay acyclic), except
    for :data:`_GENERIC_CALLEES`, whose by-name matches are noise."""
    models: List[_ModuleModel] = []
    for rel in iter_py_files(roots, repo_root):
        with open(os.path.join(repo_root, rel), "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError:
            continue
        models.append(_ModuleModel(tree, rel))

    graph = LockGraph()
    # global method index: name -> transitive lock acquisitions (repo-wide
    # fixpoint so frontend -> engine.submit_request -> scheduler.submit
    # composes into frontend._lock -> SlotScheduler._plock)
    acq: Dict[Tuple[str, Optional[str], str], Set[str]] = {}
    calls: Dict[Tuple[str, Optional[str], str],
                List[Tuple[str, int, Tuple[str, ...]]]] = {}
    by_name: Dict[str, List[Tuple[str, Optional[str], str]]] = {}
    for model in models:
        for (cls, name), m in model.methods.items():
            key = (model.path, cls, name)
            acq[key] = {a for a, _ in m.acquires}
            calls[key] = [c for c in m.calls
                          if c[0] not in _GENERIC_CALLEES]
            by_name.setdefault(name, []).append(key)

    changed = True
    while changed:
        changed = False
        for key, csites in calls.items():
            for callee, _line, _held in csites:
                for ckey in by_name.get(callee, ()):
                    if not acq[ckey] <= acq[key]:
                        acq[key] |= acq[ckey]
                        changed = True

    for model in models:
        for a, b, line, where in model.lock_edges():
            graph.add(a, b, f"{model.path}:{line} ({where})")
        for (cls, name), m in model.methods.items():
            key = (model.path, cls, name)
            for callee, line, held in m.calls:
                if not held or callee in _GENERIC_CALLEES:
                    continue
                for ckey in by_name.get(callee, ()):
                    for b in acq[ckey]:
                        for a in held:
                            graph.add(a, b,
                                      f"{model.path}:{line} "
                                      f"({cls}.{name} -> {callee})")
    return graph
