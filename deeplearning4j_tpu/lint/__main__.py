from deeplearning4j_tpu.lint.cli import main

main()
