"""graftlint AST rules — the JAX footguns this codebase actually hits.

GL001  host-sync / tracer-leak calls inside jit-traced functions
GL002  unguarded backend probes (jax.devices & co) — the round-5 driver hang
GL003  Python side effects under jit (print, global/nonlocal mutation)
GL004  PRNG key reuse without split
GL005  mutable default arguments in public APIs
GL007  bare except / swallowed exceptions
GL009  np.* inside a GRAPH_OPS / registry op impl off the numpy-static
       whitelist — silent host fallback under jit, in op-impl form
GL010  time.time() subtraction used as a duration — wall clocks jump with
       NTP; durations belong on time.perf_counter() (timestamps are fine)

(GL006 and GL008 live in rules_consistency — they need the live registries.)

Every rule is deliberately conservative: a static pass that cries wolf gets
deleted from the gate within two rounds. Heuristics and their blind spots
are documented per-rule in docs/LINT.md.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.lint.core import Finding, ast_rule

# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.experimental.pjit.pjit' for nested Attribute/Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JIT_NAMES = {"jit", "pjit"}


def _is_jit_expr(node: ast.AST) -> bool:
    """True for expressions denoting jax.jit/pjit (bare, dotted, or wrapped
    in functools.partial(jax.jit, ...))."""
    d = _dotted(node)
    if d is not None and d.split(".")[-1] in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fd = _dotted(node.func)
        if fd is not None and fd.split(".")[-1] in _JIT_NAMES:
            return True  # jax.jit(static_argnums=...) used as decorator
        if fd is not None and fd.split(".")[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _jit_functions(tree: ast.Module) -> List[ast.FunctionDef]:
    """Functions traced by jit: decorated with jit/pjit (possibly via
    partial), or a local def later wrapped as ``g = jax.jit(f)`` /
    passed directly to a jit call."""
    defs: Dict[str, ast.FunctionDef] = {}
    jitted: List[ast.FunctionDef] = []
    seen: Set[int] = set()

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
            if any(_is_jit_expr(dec) for dec in node.decorator_list):
                if id(node) not in seen:
                    seen.add(id(node))
                    jitted.append(node)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    fn = defs[arg.id]
                    if id(fn) not in seen:
                        seen.add(id(fn))
                        jitted.append(fn)
    return jitted


_NUMPY_ALIASES = {"np", "numpy", "onp", "_np", "_numpy"}


# ---------------------------------------------------------------------------
# GL001 — host sync under jit
# ---------------------------------------------------------------------------


@ast_rule("GL001", "host-sync/tracer-leak call inside a jit-traced function")
def rule_host_sync(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _jit_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                base = _dotted(f.value)
                if f.attr in ("asarray", "array") and base in _NUMPY_ALIASES:
                    findings.append(Finding(
                        path=path, line=node.lineno, rule="GL001",
                        severity="error",
                        message=f"{base}.{f.attr}() inside jit-traced "
                                f"'{fn.name}' forces a host sync / tracer "
                                f"leak; use jnp.{f.attr} or hoist out of "
                                f"the traced path"))
                elif f.attr in ("item", "tolist") and not node.args:
                    findings.append(Finding(
                        path=path, line=node.lineno, rule="GL001",
                        severity="error",
                        message=f".{f.attr}() inside jit-traced '{fn.name}' "
                                f"blocks on device and fails under trace"))
            elif (isinstance(f, ast.Name) and f.id in ("float", "int")
                  and len(node.args) == 1
                  and not isinstance(node.args[0], ast.Constant)):
                findings.append(Finding(
                    path=path, line=node.lineno, rule="GL001",
                    severity="warning",
                    message=f"{f.id}() on a traced value inside jit-traced "
                            f"'{fn.name}' concretizes the tracer"))
    return findings


# ---------------------------------------------------------------------------
# GL002 — unguarded backend probes
# ---------------------------------------------------------------------------

_PROBES = {"devices", "local_devices", "device_count", "local_device_count"}


def _mentions_subprocess_or_timeout(fn: ast.AST) -> bool:
    """Guard heuristic: the enclosing function routes the probe through a
    subprocess or bounds it with a timeout (the gate.py has_tpu pattern)."""
    for node in ast.walk(fn):
        d = _dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
        if d and ("subprocess" in d.split(".") or "Popen" in d.split(".")):
            return True
        if isinstance(node, ast.keyword) and node.arg == "timeout":
            return True
        if isinstance(node, ast.Call):
            fd = _dotted(node.func)
            if fd and fd.split(".")[-1] in ("wait_for", "alarm"):
                return True
    return False


@ast_rule("GL002", "unguarded backend probe (jax.devices & co)")
def rule_backend_probe(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []

    # enclosing-function map: node id -> innermost FunctionDef
    enclosing: Dict[int, Optional[ast.AST]] = {}

    def visit(node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            enclosing[id(child)] = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                visit(child, child)
            else:
                visit(child, fn)

    visit(tree, None)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        parts = d.split(".")
        if not (len(parts) >= 2 and parts[0] == "jax" and parts[-1] in _PROBES):
            continue
        fn = enclosing.get(id(node))
        if fn is None:
            findings.append(Finding(
                path=path, line=node.lineno, rule="GL002", severity="error",
                message=f"jax.{parts[-1]}() at import time initializes the "
                        f"backend and can hang on an unreachable TPU; move "
                        f"into a function behind a subprocess/timeout guard"))
        elif not _mentions_subprocess_or_timeout(fn):
            name = getattr(fn, "name", "<lambda>")
            findings.append(Finding(
                path=path, line=node.lineno, rule="GL002", severity="warning",
                message=f"jax.{parts[-1]}() in '{name}' has no "
                        f"subprocess/timeout guard; an unreachable backend "
                        f"hangs the caller (round-5 driver hang)"))
    return findings


# ---------------------------------------------------------------------------
# GL003 — Python side effects under jit
# ---------------------------------------------------------------------------


@ast_rule("GL003", "Python side effect inside a jit-traced function")
def rule_side_effects(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _jit_functions(tree):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                findings.append(Finding(
                    path=path, line=node.lineno, rule="GL003",
                    severity="warning",
                    message=f"print() inside jit-traced '{fn.name}' runs at "
                            f"trace time only; use jax.debug.print"))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                findings.append(Finding(
                    path=path, line=node.lineno, rule="GL003",
                    severity="error",
                    message=f"{kind} mutation inside jit-traced '{fn.name}' "
                            f"is a trace-time side effect (stale after the "
                            f"first compile)"))
    return findings


# ---------------------------------------------------------------------------
# GL004 — PRNG key reuse
# ---------------------------------------------------------------------------

# jax.random functions that CONSUME a key (same key twice => identical or
# correlated draws). Non-consuming: split/fold_in/key construction/inspection.
_NON_CONSUMING = {"split", "fold_in", "PRNGKey", "key", "wrap_key_data",
                  "clone", "key_data", "key_impl"}


def _jax_random_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(dotted prefixes bound to jax.random, bare function names imported
    from it) — so stdlib ``random`` never triggers the rule."""
    prefixes: Set[str] = set()
    bare: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    prefixes.add((a.asname or "jax") + ".random")
                elif a.name == "jax.random":
                    prefixes.add(a.asname or "jax.random")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        prefixes.add(a.asname or "random")
            elif node.module == "jax.random":
                for a in node.names:
                    bare.add(a.asname or a.name)
    return prefixes, bare


def _rebound_names(stmt: ast.AST) -> Set[str]:
    out: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    elif isinstance(stmt, ast.withitem) and stmt.optional_vars is not None:
        targets = [stmt.optional_vars]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                out.add(node.id)
    return out


class _KeyReuseScanner:
    """Branch-aware scan: mutually exclusive If/Try arms get independent
    copies of the consumed-key state (the weight-init dispatch pattern —
    twenty `if scheme == ...: return jax.random.normal(key, ...)` arms —
    is one consumption per call, not twenty). Uses inside a branch do not
    propagate out: precision over recall — a gate rule that cries wolf
    gets deleted."""

    def __init__(self, prefixes: Set[str], bare: Set[str], fn_name: str,
                 path: str):
        self.prefixes, self.bare = prefixes, bare
        self.fn_name, self.path = fn_name, path
        self.findings: List[Finding] = []

    def _leaf(self, call: ast.Call) -> Optional[str]:
        d = _dotted(call.func)
        if d is None:
            return None
        if d in self.bare:
            return d
        head, _, tail = d.rpartition(".")
        return tail if head in self.prefixes else None

    def _expr(self, node: Optional[ast.AST], consumed: Dict[str, int]) -> None:
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue  # different scope (walk still descends; acceptable)
            if not isinstance(sub, ast.Call):
                continue
            leaf = self._leaf(sub)
            if leaf is None or not sub.args:
                continue
            arg = sub.args[0]           # key is arg 0 by convention
            if not isinstance(arg, ast.Name):
                continue
            if leaf in _NON_CONSUMING:
                consumed.pop(arg.id, None)
            elif arg.id in consumed:
                # message stays line-number-free: it is part of the
                # baseline key, which must survive unrelated edits
                self.findings.append(Finding(
                    path=self.path, line=arg.lineno, rule="GL004",
                    severity="error",
                    message=f"PRNG key '{arg.id}' in '{self.fn_name}' "
                            f"consumed again without jax.random.split — "
                            f"draws are identical/correlated"))
            else:
                consumed[arg.id] = arg.lineno

    def block(self, stmts: Sequence[ast.stmt], consumed: Dict[str, int]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                self._expr(stmt.test, consumed)
                self.block(stmt.body, dict(consumed))
                self.block(stmt.orelse, dict(consumed))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter, consumed)
                body_state = dict(consumed)
                for name in _rebound_names(stmt):
                    body_state.pop(name, None)
                self.block(stmt.body, body_state)
                self.block(stmt.orelse, dict(consumed))
            elif isinstance(stmt, ast.While):
                self._expr(stmt.test, consumed)
                self.block(stmt.body, dict(consumed))
                self.block(stmt.orelse, dict(consumed))
            elif isinstance(stmt, ast.Try):
                self.block(stmt.body, dict(consumed))
                for h in stmt.handlers:
                    self.block(h.body, dict(consumed))
                self.block(stmt.orelse, dict(consumed))
                self.block(stmt.finalbody, dict(consumed))
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._expr(item.context_expr, consumed)
                    if item.optional_vars is not None:
                        for name in _rebound_names(item):
                            consumed.pop(name, None)
                self.block(stmt.body, consumed)   # runs exactly once
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested scope: scanned by its own pass
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self._expr(child, consumed)
                for name in _rebound_names(stmt):
                    consumed.pop(name, None)


@ast_rule("GL004", "PRNG key consumed twice without split")
def rule_key_reuse(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []
    prefixes, bare = _jax_random_aliases(tree)
    if not prefixes and not bare:
        return findings
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scanner = _KeyReuseScanner(prefixes, bare, fn.name, path)
        scanner.block(fn.body, {})
        findings.extend(scanner.findings)
    return findings


# ---------------------------------------------------------------------------
# GL005 — mutable default arguments in public APIs
# ---------------------------------------------------------------------------


@ast_rule("GL005", "mutable default argument in a public API")
def rule_mutable_defaults(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name.startswith("_"):
            continue
        defaults = list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None]
        for d in defaults:
            bad = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set"))
            if bad:
                findings.append(Finding(
                    path=path, line=d.lineno, rule="GL005",
                    severity="warning",
                    message=f"mutable default argument in public "
                            f"'{fn.name}' is shared across calls; default "
                            f"to None and build inside"))
    return findings


# ---------------------------------------------------------------------------
# GL009 — numpy inside graph-op implementations
# ---------------------------------------------------------------------------

# ops whose impls are DOCUMENTED numpy-static (docs/LINT.md, docs/
# ANALYSIS.md): they deliberately stay on host so imported
# tf.shape→Pack→Reshape chains keep trace-time-concrete ints. Everything
# else reaching np.* under a jit trace is the round-5 hang class in
# op-impl form: a silent device→host sync (or a tracer leak) every step.
NUMPY_STATIC_OP_WHITELIST = frozenset(["shape_of", "stack", "unstack"])

_OP_DECORATOR_NAMES = {"op", "_op"}
_OP_REGISTER_METHODS = {"register"}


def _graph_op_impls(tree: ast.Module):
    """Yield (op_name, function-or-lambda node) for every statically
    recognizable graph-op implementation:

    * values of a dict literal assigned to ``GRAPH_OPS``;
    * ``GRAPH_OPS["name"] = <lambda | local def>`` (any ``*GRAPH_OPS``
      spelling — importers patch the table under aliases);
    * functions decorated ``@op("name")`` / ``@_op("name")`` (the
      declarable-op registry idiom);
    * ``<reg>.register("name", fn)`` with a local ``def fn``.
    """
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    for node in ast.walk(tree):
        # GRAPH_OPS = { "name": <lambda>, ... } — plain OR annotated
        # (the real table is `GRAPH_OPS: Dict[str, Callable] = {...}`)
        dict_targets = []
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            dict_targets = list(node.targets)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.value, ast.Dict):
            dict_targets = [node.target]
        for tgt in dict_targets:
            name = _dotted(tgt)
            if name is None or not name.split(".")[-1].endswith("GRAPH_OPS"):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    impl = v if isinstance(v, ast.Lambda) else (
                        defs.get(v.id) if isinstance(v, ast.Name) else None)
                    if impl is not None:
                        yield k.value, impl
        # GRAPH_OPS["name"] = impl
        if isinstance(node, ast.Assign) and node.targets and \
                isinstance(node.targets[0], ast.Subscript):
            sub = node.targets[0]
            name = _dotted(sub.value)
            if name is not None and name.split(".")[-1].endswith("GRAPH_OPS"):
                key = sub.slice
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    impl = node.value if isinstance(node.value, ast.Lambda) \
                        else (defs.get(node.value.id)
                              if isinstance(node.value, ast.Name) else None)
                    if impl is not None:
                        yield key.value, impl
        # @op("name") / @_op("name") def impl(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and dec.args and \
                        isinstance(dec.args[0], ast.Constant) and \
                        isinstance(dec.args[0].value, str):
                    d = _dotted(dec.func)
                    if d is not None and d.split(".")[-1] in _OP_DECORATOR_NAMES:
                        yield dec.args[0].value, node
        # reg.register("name", fn)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _OP_REGISTER_METHODS \
                and len(node.args) >= 2 \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            impl = node.args[1] if isinstance(node.args[1], ast.Lambda) else (
                defs.get(node.args[1].id)
                if isinstance(node.args[1], ast.Name) else None)
            if impl is not None:
                yield node.args[0].value, impl


@ast_rule("GL009", "np.* inside a graph-op impl off the numpy-static whitelist")
def rule_numpy_in_op_impl(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for op_name, impl in _graph_op_impls(tree):
        if op_name in NUMPY_STATIC_OP_WHITELIST:
            continue
        for node in ast.walk(impl):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            base = _dotted(f.value)
            if base not in _NUMPY_ALIASES:
                continue
            key = (op_name, node.lineno)
            if key in seen:  # one op impl can be yielded via two idioms
                continue
            seen.add(key)
            findings.append(Finding(
                path=path, line=node.lineno, rule="GL009",
                severity="error",
                message=f"{base}.{f.attr}() inside graph-op impl "
                        f"'{op_name}' runs on host under jit (silent "
                        f"fallback / tracer leak); use jnp, or add the op "
                        f"to the documented numpy-static whitelist "
                        f"(shape_of/stack/unstack) with justification"))
    return findings


# ---------------------------------------------------------------------------
# GL010 — wall-clock subtraction used as a duration
# ---------------------------------------------------------------------------


def _walltime_aliases(tree: ast.Module) -> Set[str]:
    """Dotted spellings that denote ``time.time`` in this module:
    ``{"time.time"}`` under ``import time`` (any asname), plus bare names
    from ``from time import time``. Stdlib-only — a local ``def time()``
    never registers because it is not an import."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    out.add((a.asname or "time") + ".time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    out.add(a.asname or "time")
    return out


def _is_walltime_call(node: ast.AST, aliases: Set[str]) -> bool:
    return (isinstance(node, ast.Call) and not node.args
            and _dotted(node.func) in aliases)


@ast_rule("GL010", "time.time() subtraction used as a duration")
def rule_walltime_duration(tree, lines, path) -> List[Finding]:
    """``time.time()`` is a WALL clock: NTP steps/slews move it, so a
    subtraction of two readings is not a duration — it can be negative or
    hours off, silently corrupting training-time stats, ETA math, and time
    budgets (the reference's PerformanceListener class of bugs).

    Flagged: ``a - b`` where BOTH operands are wall-time readings — a
    direct ``time.time()`` call or a name/attribute assigned from one
    anywhere in the module (``self._t0 = time.time()`` in ``__init__``,
    subtracted in another method, is the repo's own pattern). Requiring
    both sides keeps timestamps whitelisted: ``time.time() - 86400``
    (epoch arithmetic) and plain timestamp fields never fire. Blind spot
    (documented in docs/LINT.md): deadline COMPARISONS
    (``time.time() > t0 + budget``) are not subtractions and pass."""
    aliases = _walltime_aliases(tree)
    if not aliases:
        return []
    timeish: Set[str] = set()
    for node in ast.walk(tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign) and \
                _is_walltime_call(node.value, aliases):
            targets = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) and \
                node.value is not None and \
                _is_walltime_call(node.value, aliases):
            targets = [node.target]
        for t in targets:
            name = _dotted(t)
            if name:
                timeish.add(name)

    def is_timeish(node: ast.AST) -> bool:
        if _is_walltime_call(node, aliases):
            return True
        d = _dotted(node)
        return d is not None and d in timeish

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and is_timeish(node.left) and is_timeish(node.right):
            findings.append(Finding(
                path=path, line=node.lineno, rule="GL010", severity="error",
                message="time.time() subtraction used as a duration — the "
                        "wall clock jumps with NTP; use time.perf_counter() "
                        "for both readings (timestamps themselves are fine)"))
    return findings


# ---------------------------------------------------------------------------
# GL007 — bare / swallowed exceptions
# ---------------------------------------------------------------------------

_BROAD = {"Exception", "BaseException"}


@ast_rule("GL007", "bare except / swallowed exception")
def rule_bare_except(tree, lines, path) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                path=path, line=node.lineno, rule="GL007", severity="error",
                message="bare 'except:' catches KeyboardInterrupt/SystemExit;"
                        " name the exception"))
            continue
        type_name = _dotted(node.type)
        broad = type_name is not None and type_name.split(".")[-1] in _BROAD
        body_is_pass = all(isinstance(s, ast.Pass) for s in node.body)
        if broad and body_is_pass:
            findings.append(Finding(
                path=path, line=node.lineno, rule="GL007", severity="warning",
                message=f"'except {type_name}: pass' swallows every error "
                        f"silently; log or narrow it"))
    return findings
