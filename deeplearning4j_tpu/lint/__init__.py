"""graftlint — JAX-footgun static analysis wired into the gate.

The suite-time analysis the round-5 verdict asked for: tracer leaks,
import-time backend probes (the driver-hang class), side effects under jit,
PRNG reuse, registry shadowing, and README surface-count drift — reported
with rule IDs and diffed against a committed, shrink-only baseline.

Rule catalog and workflow: docs/LINT.md.  CLI: ``python -m
deeplearning4j_tpu.lint`` or ``make lint``.

Importing this package (and running the AST rules) needs no jax; only the
consistency rules in ``rules_consistency`` load the live registries.
"""

from deeplearning4j_tpu.lint.core import (  # noqa: F401
    AST_RULES, Finding, diff_baseline, iter_py_files, lint_paths,
    lint_source, load_baseline, write_baseline)

# register the AST rules on import (graftlock — the GL011-GL014 lock
# discipline tier —, graftshape — the GS001-GS005 jit-signature tier —
# and graftlife — the GR001-GR005 resource-lifecycle tier — ride the
# same registry; see rules_concurrency / rules_shape / rules_lifecycle)
from deeplearning4j_tpu.lint import rules_ast  # noqa: F401
from deeplearning4j_tpu.lint import rules_concurrency  # noqa: F401
from deeplearning4j_tpu.lint import rules_shape  # noqa: F401
from deeplearning4j_tpu.lint import rules_lifecycle  # noqa: F401

__all__ = ["AST_RULES", "Finding", "diff_baseline", "iter_py_files",
           "lint_paths", "lint_source", "load_baseline", "write_baseline"]
