"""graftlife — resource-lifecycle & exactly-once static analysis.

GR001  unbalanced page ownership: a refcounted-page acquisition
       (``alloc_page``/``retain``/``cow_page``/``map_shared``) that some
       path — including raise and early-return paths — exits without a
       matching ``release``/``free_slot``/tree-insert handoff; plus the
       call-graph arm: a call to a page-acquiring intra-module callee
       sitting OUTSIDE the raise-unwind protection its sibling
       admission path has (the engine-step leak shape)
GR002  double-release hazard: a second ``release`` of the same page
       reference on one path, or two release-loops draining the same
       page list
GR003  terminal-taxonomy exactly-once: a function that completes a
       request future (``set_result``/``set_exception``, including the
       deferred-lambda form) without routing the outcome through the
       ``count_terminal`` funnel (or a funnel-calling helper); plus the
       double-count arm (two ``count_terminal`` on one straight line)
GR004  unstoppable thread: a started ``Thread(...)`` with no
       join/stop reachable (class-level for ``self._thread`` workers,
       function-level for locals) — ``daemon=True`` does NOT exempt,
       only a written justification does
GR005  non-atomic durable write: ``open(.., "w")``/``np.save*`` into a
       durable file without the tmp + ``os.replace`` dance in the same
       function (and not itself writing the ``*.tmp`` side)

Same house rules as graftlock/graftshape: deliberately conservative
(precision over recall — a gate rule that cries wolf gets deleted),
blind spots documented in docs/LINT.md, and a true positive the code
*means* is suppressed inline with ``# graftlife: justified(GR00x):
<reason>`` — the reason is mandatory; a bare marker does not suppress.

Beyond the per-file rules this module exports the repo-wide static
ownership inventory (:func:`static_ownership_inventory`): every
function span that touches the allocator vocabulary, in span units the
runtime resource tracer (``testing/lifetrace.py``) checks observed
acquire/release callsites against — an observed callsite outside the
inventory is an analyzer blind spot, not a baseline candidate.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.lint.core import Finding, ast_rule, iter_py_files

GR_RULES = ("GR001", "GR002", "GR003", "GR004", "GR005")

# ---------------------------------------------------------------------------
# inline justification (the graftlife analog of "graftlint: disable")
# ---------------------------------------------------------------------------

_JUSTIFIED_RE = re.compile(
    r"graftlife:\s*justified\((GR\d{3})\)\s*:\s*(\S.*)")


def _justified_lines(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """1-based line -> rule ids justified there. Only matches carrying a
    nonempty written reason suppress — acceptance requires every
    justified site to say WHY."""
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, start=1):
        for m in _JUSTIFIED_RE.finditer(text):
            out.setdefault(i, set()).add(m.group(1))
    return out


def _apply_justified(findings: List[Finding],
                     lines: Sequence[str]) -> List[Finding]:
    """A justification suppresses a finding on its own line or anywhere in
    the contiguous comment block directly above it (real reasons often run
    to two or three comment lines)."""
    just = _justified_lines(lines)

    def _suppressed(f: Finding) -> bool:
        if f.rule in just.get(f.line, ()):
            return True
        ln = f.line - 1
        while 1 <= ln <= len(lines) and lines[ln - 1].lstrip().startswith("#"):
            if f.rule in just.get(ln, ()):
                return True
            ln -= 1
        return False

    return [f for f in findings if not _suppressed(f)]


def _in_library(path: str) -> bool:
    """The lifecycle rules cover library code; bench/driver scripts in
    tools/ and examples/ own their throwaway threads and futures."""
    return not (path.startswith("tools/") or path.startswith("examples/"))


# ---------------------------------------------------------------------------
# the ownership vocabulary (serving/cache.py's allocator + the radix tree)
# ---------------------------------------------------------------------------

# value-returning acquisitions: ``p = cache.alloc_page()`` binds a ref
_ALLOC_METHODS = {"alloc_page", "cow_page"}
# every acquisition the refcount bookkeeping must balance
_ACQUIRE_METHODS = {"alloc_page", "cow_page", "retain", "map_shared"}
# tree-insert hands pages to the radix tree (insert() retains what it
# keeps — the documented handoff convention, docs/ROBUSTNESS.md)
_HANDOFF_METHODS = {"insert"}
# terminal funnels: count_terminal itself plus the helpers that call it
# (scheduler.fail_all/fail_pending count per future; engine
# _finish_unslotted counts; frontend _deny counts)
_TERMINAL_FUNNELS = {"count_terminal", "fail_all", "fail_pending",
                     "_finish_unslotted", "_deny"}
_COMPLETERS = {"set_result", "set_exception"}

# by-name intra-module call resolution must not alias through names every
# builtin container also has (graftlock's precedent)
_GENERIC_CALLEES = (set(dir(list)) | set(dir(dict)) | set(dir(set))
                    | set(dir(str)) | set(dir(bytes))
                    | {"min", "max", "sum", "len", "start", "run", "join",
                       "acquire", "release", "wait", "notify", "put",
                       "submit", "result", "insert"})


def _call_name(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_release_call(node: ast.Call) -> bool:
    """``X.release(p)`` (with a page argument — ``lock.release()`` takes
    none) or ``X.free_slot(...)``."""
    name = _call_name(node)
    if name == "free_slot":
        return True
    return name == "release" and bool(node.args)


def _is_acquire_call(node: ast.Call) -> bool:
    return _call_name(node) in _ACQUIRE_METHODS


def _walk_no_defs(node: ast.AST):
    """Walk an AST without descending into nested function/class bodies
    or lambdas — closure bodies run later, on someone else's path."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# GR001/GR002 — the per-function ownership path simulation
# ---------------------------------------------------------------------------


class _PathState:
    """Held page refs (name -> acquisition line) and already-released
    refs along one abstract path."""

    __slots__ = ("held", "released")

    def __init__(self, held: Optional[Dict[str, int]] = None,
                 released: Optional[Dict[str, int]] = None):
        self.held: Dict[str, int] = dict(held or {})
        self.released: Dict[str, int] = dict(released or {})

    def copy(self) -> "_PathState":
        return _PathState(self.held, self.released)

    @staticmethod
    def merge(states: List["_PathState"]) -> "_PathState":
        """Join of fall-through branches: a ref is held after the join if
        it is still held on ANY branch (might-be-held is what leak exits
        must see)."""
        out = _PathState()
        for st in states:
            for k, v in st.held.items():
                out.held.setdefault(k, v)
            for k, v in st.released.items():
                out.released.setdefault(k, v)
        return out


class _Exit:
    __slots__ = ("kind", "line", "held")

    def __init__(self, kind: str, line: int, held: Dict[str, int]):
        self.kind = kind
        self.line = line
        self.held = dict(held)


class _FnSim:
    """Abstract interpretation of one function body: tracks named page
    acquisitions and reports every exit (return / raise / fall-through)
    that still holds a reference, plus double releases on a path.

    Ownership transfer discharges a held name: released/free_slot'ed,
    handed to the radix tree (``insert``), returned to the caller,
    stored into an attribute/subscript/container, or passed as an
    argument to ANY call (the callee — e.g. an intra-module helper that
    releases its parameter — now owns it; precision over recall)."""

    def __init__(self, func: ast.AST):
        self.func = func
        self.exits: List[_Exit] = []
        self.double: List[Tuple[str, int]] = []
        self.acquires = False  # any acquisition vocabulary in the body

    # -- expression scanning -------------------------------------------------
    def _calls_in(self, node: ast.AST) -> List[ast.Call]:
        # the node itself first: _walk_no_defs yields children only, and
        # a statement like ``cache.release(p)`` IS the top-level Call
        head = [node] if isinstance(node, ast.Call) else []
        return head + [n for n in _walk_no_defs(node)
                       if isinstance(n, ast.Call)]

    def _arg_names(self, call: ast.Call) -> List[str]:
        names = [a.id for a in call.args if isinstance(a, ast.Name)]
        names += [k.value.id for k in call.keywords
                  if isinstance(k.value, ast.Name)]
        # a list literal argument transfers its held elements too:
        # tree.insert(prompt, [p1, p2])
        for a in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(a, (ast.List, ast.Tuple)):
                names += [e.id for e in a.elts if isinstance(e, ast.Name)]
        return names

    def _scan_calls(self, node: ast.AST, st: _PathState) -> None:
        for call in self._calls_in(node):
            name = _call_name(call)
            if name in _ACQUIRE_METHODS:
                self.acquires = True
            if _is_release_call(call):
                if name == "free_slot":
                    # free_slot releases every page the slot owns — all
                    # slot-attributed ownership in flight is discharged
                    st.held.clear()
                    continue
                arg = call.args[0]
                if isinstance(arg, ast.Name):
                    if arg.id in st.released:
                        self.double.append((arg.id, call.lineno))
                    elif arg.id in st.held:
                        st.released[arg.id] = call.lineno
                        del st.held[arg.id]
                continue
            # any other call that receives a held name transfers
            # ownership to the callee/container (append, insert, a
            # helper that releases its parameter, a ctor that keeps it)
            for n in self._arg_names(call):
                if n in st.held:
                    del st.held[n]

    def _discharge_names_in(self, node: ast.AST, st: _PathState) -> None:
        # the node itself first: ``return p`` hands over a bare Name and
        # _walk_no_defs yields children only
        for n in [node] + list(_walk_no_defs(node)):
            if isinstance(n, ast.Name) and n.id in st.held:
                del st.held[n.id]

    # -- None-guard specialization -------------------------------------------
    @staticmethod
    def _none_guard(test: ast.AST) -> Tuple[Optional[str], Optional[str]]:
        """(name dropped in the TRUE branch, name dropped in the FALSE
        branch) for the allocator's None-on-exhaustion contract:
        ``if p is None: return`` holds nothing on the failure branch."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.left, ast.Name) and \
                len(test.comparators) == 1 and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return test.left.id, None
            if isinstance(test.ops[0], ast.IsNot):
                return None, test.left.id
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            return test.operand.id, None
        if isinstance(test, ast.Name):
            return None, test.id
        return None, None

    # -- statement interpretation --------------------------------------------
    def _block(self, stmts: List[ast.stmt],
               st: _PathState) -> Optional[_PathState]:
        """Returns the fall-through state, or None when every path in
        the block exits the function."""
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Assign):
                self._scan_calls(s.value, st)
                tgt = s.targets[0] if len(s.targets) == 1 else None
                if isinstance(tgt, ast.Name) and \
                        isinstance(s.value, ast.Call) and \
                        _call_name(s.value) in _ALLOC_METHODS:
                    st.held[tgt.id] = s.lineno
                    st.released.pop(tgt.id, None)
                    self.acquires = True
                elif tgt is not None and not isinstance(tgt, ast.Name):
                    # stored into an attribute/subscript — transferred
                    self._discharge_names_in(s.value, st)
                elif isinstance(tgt, ast.Name) and tgt.id in st.held:
                    # rebinding a held name loses our handle (blind spot:
                    # treated as a transfer, not a leak)
                    del st.held[tgt.id]
                continue
            if isinstance(s, (ast.AugAssign, ast.AnnAssign)):
                if s.value is not None:
                    self._scan_calls(s.value, st)
                continue
            if isinstance(s, ast.Expr):
                self._scan_calls(s.value, st)
                continue
            if isinstance(s, ast.Return):
                if s.value is not None:
                    self._scan_calls(s.value, st)
                    self._discharge_names_in(s.value, st)
                self.exits.append(_Exit("return", s.lineno, st.held))
                return None
            if isinstance(s, ast.Raise):
                self.exits.append(_Exit("raise", s.lineno, st.held))
                return None
            if isinstance(s, ast.If):
                self._scan_calls(s.test, st)
                t_st, f_st = st.copy(), st.copy()
                drop_true, drop_false = self._none_guard(s.test)
                if drop_true:
                    t_st.held.pop(drop_true, None)
                if drop_false:
                    f_st.held.pop(drop_false, None)
                rt = self._block(s.body, t_st)
                rf = self._block(s.orelse, f_st) if s.orelse else f_st
                live = [x for x in (rt, rf) if x is not None]
                if not live:
                    return None
                merged = _PathState.merge(live)
                st.held, st.released = merged.held, merged.released
                continue
            if isinstance(s, (ast.For, ast.AsyncFor)):
                self._scan_calls(s.iter, st)
                body_st = st.copy()
                if isinstance(s.target, ast.Name):
                    body_st.held.pop(s.target.id, None)
                rb = self._block(s.body, body_st)
                live = [st] + ([rb] if rb is not None else [])
                merged = _PathState.merge(live)
                st.held, st.released = merged.held, merged.released
                continue
            if isinstance(s, ast.While):
                self._scan_calls(s.test, st)
                body_st = st.copy()
                rb = self._block(s.body, body_st)
                live = [st] + ([rb] if rb is not None else [])
                merged = _PathState.merge(live)
                st.held, st.released = merged.held, merged.released
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    self._scan_calls(item.context_expr, st)
                r = self._block(s.body, st)
                if r is None:
                    return None
                continue
            if isinstance(s, ast.Try):
                r = self._try(s, st)
                if r is None:
                    return None
                st.held, st.released = r.held, r.released
                continue
            # everything else (pass/assert/del/global/break/continue...):
            # scan embedded expressions for calls
            self._scan_calls(s, st)
        return st

    def _finally_discharges(self, finalbody: List[ast.stmt]
                            ) -> Tuple[Set[str], bool]:
        """(names discharged, clears-everything) for a finally block:
        applied to every exit recorded inside the guarded region."""
        names: Set[str] = set()
        clears = False
        for s in finalbody:
            for call in (n for n in _walk_no_defs(s)
                         if isinstance(n, ast.Call)):
                if _call_name(call) == "free_slot":
                    clears = True
                elif _is_release_call(call):
                    if call.args and isinstance(call.args[0], ast.Name):
                        names.add(call.args[0].id)
                else:
                    names.update(n for n in self._arg_names(call))
        return names, clears

    def _try(self, s: ast.Try, st: _PathState) -> Optional[_PathState]:
        mark = len(self.exits)
        body_st = st.copy()
        rb = self._block(s.body, body_st)
        raised = [e for e in self.exits[mark:] if e.kind == "raise"]
        if s.handlers:
            # a handler intercepts in-body raises; the handler may see
            # anything acquired at ANY point of the body still held
            self.exits[mark:] = [e for e in self.exits[mark:]
                                 if e.kind != "raise"]
            entry = _PathState.merge([st, body_st if rb is None else rb])
            for e in raised:
                for k, v in e.held.items():
                    entry.held.setdefault(k, v)
            entry.released = dict(st.released)
            live: List[_PathState] = []
            if rb is not None:
                live.append(rb)
            for h in s.handlers:
                h_st = entry.copy()
                rh = self._block(h.body, h_st)
                if rh is not None:
                    live.append(rh)
        else:
            live = [rb] if rb is not None else []
        if s.finalbody:
            names, clears = self._finally_discharges(s.finalbody)
            for e in self.exits[mark:]:
                if clears:
                    e.held.clear()
                for n in names:
                    e.held.pop(n, None)
            for x in live:
                r = self._block(s.finalbody, x)
                if r is None:
                    return None
        if not live:
            return None
        out = _PathState.merge(live)
        if s.orelse:
            r = self._block(s.orelse, out)
            if r is None:
                return None
            out = r
        return out

    # -- entry ---------------------------------------------------------------
    def run(self) -> None:
        st = _PathState()
        end = self._block(self.func.body, st)
        if end is not None:
            last = getattr(self.func, "end_lineno", self.func.lineno)
            self.exits.append(_Exit("fall-through", last, end.held))

    def leaks(self) -> List[Tuple[str, int, str, int]]:
        """(name, acq_line, exit_kind, exit_line), one per leaked ref."""
        seen: Set[Tuple[str, int]] = set()
        out = []
        for e in self.exits:
            for name, acq in e.held.items():
                if (name, acq) in seen:
                    continue
                seen.add((name, acq))
                out.append((name, acq, e.kind, e.line))
        return out


# ---------------------------------------------------------------------------
# the per-module lifecycle model (cached on the tree, graftlock-style)
# ---------------------------------------------------------------------------


class _LifeModel:
    """Functions/methods of one module with their lifecycle summaries:
    which acquire page ownership (directly or through the intra-module
    call graph), which funnel terminal outcomes, and the raw nodes for
    the per-rule passes."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.functions: Dict[Tuple[Optional[str], str], ast.AST] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[(None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.functions[(node.name, sub.name)] = sub
        self._names = {name for (_cls, name) in self.functions}

        self.direct_acquires: Set[Tuple[Optional[str], str]] = set()
        self.direct_counts: Set[Tuple[Optional[str], str]] = set()
        self.calls: Dict[Tuple[Optional[str], str],
                         List[Tuple[str, int]]] = {}
        for key, fn in self.functions.items():
            callees: List[Tuple[str, int]] = []
            for n in _walk_no_defs(fn):
                if not isinstance(n, ast.Call):
                    continue
                name = _call_name(n)
                if name in _ACQUIRE_METHODS:
                    self.direct_acquires.add(key)
                if name == "count_terminal":
                    self.direct_counts.add(key)
                if name in self._names and name not in _GENERIC_CALLEES:
                    callees.append((name, n.lineno))
            self.calls[key] = callees

    def _fixpoint(self, seed: Set[Tuple[Optional[str], str]]
                  ) -> Set[Tuple[Optional[str], str]]:
        marked = set(seed)
        marked_names = {name for (_c, name) in marked}
        changed = True
        while changed:
            changed = False
            for key, callees in self.calls.items():
                if key in marked:
                    continue
                if any(name in marked_names for name, _ln in callees):
                    marked.add(key)
                    marked_names.add(key[1])
                    changed = True
        return marked

    def acquiring(self) -> Set[Tuple[Optional[str], str]]:
        """Functions that acquire page ownership, transitively through
        the intra-module call graph (graftlock's held-lock fixpoint,
        applied to ownership)."""
        return self._fixpoint(self.direct_acquires)

    def counting(self) -> Set[str]:
        """Names of module functions that transitively reach
        count_terminal — module-local funnels for GR003."""
        return {name for (_c, name) in self._fixpoint(self.direct_counts)}


def _model(tree: ast.Module, path: str) -> _LifeModel:
    model = getattr(tree, "_graftlife_model", None)
    if model is None or model.path != path:
        model = _LifeModel(tree, path)
        tree._graftlife_model = model
    return model


def _qual(key: Tuple[Optional[str], str]) -> str:
    cls, name = key
    return f"{cls}.{name}" if cls else name


# ---------------------------------------------------------------------------
# GR001 — unbalanced page ownership
# ---------------------------------------------------------------------------


def _release_unwind_trys(fn: ast.AST) -> List[ast.Try]:
    """Try statements whose handler or finally discharges page
    ownership (release/free_slot) — the function's raise-unwind
    protection for admission paths."""
    out = []
    for n in _walk_no_defs(fn):
        if not isinstance(n, ast.Try):
            continue
        cleanup = [s for h in n.handlers for s in h.body] + list(n.finalbody)
        for s in cleanup:
            if any(_is_release_call(c) for c in ast.walk(s)
                   if isinstance(c, ast.Call)):
                out.append(n)
                break
    return out


@ast_rule("GR001", "unbalanced page ownership: an alloc/retain/cow/"
                   "map_shared acquisition that a path (incl. raise/"
                   "early-return) exits without release/free_slot/"
                   "tree-handoff")
def rule_page_ownership(tree, lines, path) -> List[Finding]:
    if not _in_library(path):
        return []
    model = _model(tree, path)
    findings: List[Finding] = []
    acquiring = model.acquiring()
    acquiring_names = {name for (_c, name) in acquiring}
    for key, fn in model.functions.items():
        sim = _FnSim(fn)
        sim.run()
        for name, acq, kind, _exit_line in sim.leaks():
            findings.append(Finding(path, acq, "GR001", "error",
                f"page ref '{name}' acquired in {_qual(key)}() can exit "
                f"via {kind} without release/free_slot/handoff"))
        # the call-graph arm: sibling admission calls are protected by a
        # raise-unwind that releases, this acquiring call is not — the
        # engine-step leak shape (an exception between remove_pending
        # and admit leaks every page already mapped to the slot)
        trys = _release_unwind_trys(fn)
        if not trys:
            continue
        protected = [(t.lineno, getattr(t, "end_lineno", t.lineno))
                     for t in trys]
        for callee, line in model.calls.get(key, ()):
            if callee not in acquiring_names:
                continue
            if any(a <= line <= b for a, b in protected):
                continue
            findings.append(Finding(path, line, "GR001", "error",
                f"{_qual(key)}() calls page-acquiring '{callee}' outside "
                f"the raise-unwind protection its sibling admission path "
                f"has — an exception here leaks the mapped pages"))
    return _apply_justified(findings, lines)


# ---------------------------------------------------------------------------
# GR002 — double-release hazard
# ---------------------------------------------------------------------------


@ast_rule("GR002", "double-release hazard: a second release of the same "
                   "page ref on one path, or two release-loops draining "
                   "the same page list")
def rule_double_release(tree, lines, path) -> List[Finding]:
    if not _in_library(path):
        return []
    model = _model(tree, path)
    findings: List[Finding] = []
    for key, fn in model.functions.items():
        sim = _FnSim(fn)
        sim.run()
        for name, line in sim.double:
            findings.append(Finding(path, line, "GR002", "error",
                f"page ref '{name}' released twice on one path in "
                f"{_qual(key)}() — the second release corrupts the "
                f"refcount (or trips the allocator's assertion)"))
        # two loops draining the SAME page list both release per element
        release_loops: Dict[str, int] = {}
        for n in _walk_no_defs(fn):
            if not isinstance(n, (ast.For, ast.AsyncFor)):
                continue
            if not isinstance(n.iter, ast.Name) or \
                    not isinstance(n.target, ast.Name):
                continue
            body_releases = any(
                _is_release_call(c) and c.args
                and isinstance(c.args[0], ast.Name)
                and c.args[0].id == n.target.id
                for s in n.body for c in ast.walk(s)
                if isinstance(c, ast.Call))
            if not body_releases:
                continue
            if n.iter.id in release_loops:
                findings.append(Finding(path, n.lineno, "GR002", "error",
                    f"{_qual(key)}() releases the pages of "
                    f"'{n.iter.id}' in two separate loops — every "
                    f"element is double-released"))
            else:
                release_loops[n.iter.id] = n.lineno
    return _apply_justified(findings, lines)


# ---------------------------------------------------------------------------
# GR003 — terminal-taxonomy exactly-once
# ---------------------------------------------------------------------------


@ast_rule("GR003", "terminal-taxonomy exactly-once: a future completed "
                   "(set_result/set_exception, incl. deferred lambdas) "
                   "without routing through the count_terminal funnel")
def rule_terminal_exactly_once(tree, lines, path) -> List[Finding]:
    if not _in_library(path):
        return []
    model = _model(tree, path)
    funnels = _TERMINAL_FUNNELS | model.counting()
    findings: List[Finding] = []
    for key, fn in model.functions.items():
        completer_line: Optional[int] = None
        has_funnel = False
        # completion sites INCLUDE lambda/closure bodies — the deferred-
        # completion idiom must still pair with a count in the same
        # function (the frontend's _deny shape)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name in _COMPLETERS and completer_line is None:
                completer_line = n.lineno
            if name in funnels:
                has_funnel = True
        if completer_line is not None and not has_funnel:
            findings.append(Finding(path, completer_line, "GR003", "error",
                f"{_qual(key)}() completes a request future without "
                f"routing the outcome through the count_terminal "
                f"funnel — the terminal taxonomy loses this exit"))
        # double-count arm: two count_terminal calls in one suite (no
        # branch between them) count one request exit twice
        for n in [fn] + list(_walk_no_defs(fn)):
            for field in ("body", "orelse", "finalbody"):
                suite = getattr(n, field, None)
                if not isinstance(suite, list):
                    continue
                direct = [s for s in suite if isinstance(s, ast.Expr)
                          and isinstance(s.value, ast.Call)
                          and _call_name(s.value) == "count_terminal"]
                if len(direct) >= 2:
                    findings.append(Finding(path, direct[1].lineno, "GR003", "error",
                        f"{_qual(key)}() counts count_terminal twice on "
                        f"one straight-line path — one request exit "
                        f"would increment two terminal labels"))
    return _apply_justified(findings, lines)


# ---------------------------------------------------------------------------
# GR004 — unstoppable thread
# ---------------------------------------------------------------------------


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "Thread"
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _has_daemon_kwarg(call: ast.Call) -> bool:
    return any(k.arg == "daemon" and isinstance(k.value, ast.Constant)
               and k.value.value for k in call.keywords)


def _fn_has_join(fn: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and isinstance(n.func, ast.Attribute)
               and n.func.attr in ("join", "wait_until_finished")
               for n in ast.walk(fn))


@ast_rule("GR004", "unstoppable thread: a started Thread with no "
                   "join/stop reachable from any shutdown path "
                   "(daemon=True does not exempt — justify it)")
def rule_unstoppable_thread(tree, lines, path) -> List[Finding]:
    if not _in_library(path):
        return []
    model = _model(tree, path)
    findings: List[Finding] = []
    # class-level: a worker stored on self is stoppable iff some method
    # of the class joins (the stop()/close() convention); blind spot:
    # join-presence is per-class, not matched to the exact attribute
    joining_classes = {cname for cname, cnode in model.classes.items()
                       if any(_fn_has_join(m) for m in cnode.body
                              if isinstance(m, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)))}
    for (cls, name), fn in model.functions.items():
        stoppable_class = cls in joining_classes
        fn_joins = _fn_has_join(fn)
        for n in _walk_no_defs(fn):
            if isinstance(n, ast.Assign) and _is_thread_ctor(n.value):
                tgt = n.targets[0] if len(n.targets) == 1 else None
                stored_on_self = isinstance(tgt, ast.Attribute)
                if stored_on_self and stoppable_class:
                    continue
                if isinstance(tgt, ast.Name) and (fn_joins or
                                                  stoppable_class):
                    # a local worker joined in-function, or handed to
                    # the class's joining shutdown path
                    continue
                daemon = _has_daemon_kwarg(n.value)
                findings.append(Finding(path, n.lineno, "GR004", "error",
                    f"thread started in {_qual((cls, name))}() has no "
                    f"reachable join/stop — an unstoppable thread"
                    + (" (daemon=True needs a written justification)"
                       if daemon else "")))
            elif isinstance(n, ast.Expr) and isinstance(n.value, ast.Call) \
                    and isinstance(n.value.func, ast.Attribute) \
                    and n.value.func.attr == "start" \
                    and _is_thread_ctor(n.value.func.value):
                # inline Thread(...).start(): nothing can ever join it
                daemon = _has_daemon_kwarg(n.value.func.value)
                findings.append(Finding(path, n.lineno, "GR004", "error",
                    f"anonymous Thread(...).start() in "
                    f"{_qual((cls, name))}() can never be joined — an "
                    f"unstoppable thread"
                    + (" (daemon=True needs a written justification)"
                       if daemon else "")))
    return _apply_justified(findings, lines)


# ---------------------------------------------------------------------------
# GR005 — non-atomic durable write
# ---------------------------------------------------------------------------

_NP_SAVERS = {"save", "savez", "savez_compressed"}


def _expr_mentions_tmp(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "tmp" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and ".tmp" in n.value:
            return True
    return False


def _fn_has_replace(fn: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and isinstance(n.func, ast.Attribute)
               and n.func.attr in ("replace", "rename")
               and isinstance(n.func.value, ast.Name)
               and n.func.value.id == "os"
               for n in ast.walk(fn))


@ast_rule("GR005", "non-atomic durable write: open(.., 'w')/np.save* "
                   "without the tmp + os.replace dance — a torn write "
                   "publishes a corrupt file")
def rule_atomic_durable_write(tree, lines, path) -> List[Finding]:
    if not _in_library(path):
        return []
    model = _model(tree, path)
    findings: List[Finding] = []
    for key, fn in model.functions.items():
        has_replace = _fn_has_replace(fn)
        for n in _walk_no_defs(fn):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            target: Optional[ast.AST] = None
            what = None
            if isinstance(n.func, ast.Name) and n.func.id == "open" \
                    and n.args:
                mode = None
                if len(n.args) >= 2 and isinstance(n.args[1], ast.Constant):
                    mode = n.args[1].value
                for k in n.keywords:
                    if k.arg == "mode" and isinstance(k.value, ast.Constant):
                        mode = k.value.value
                if isinstance(mode, str) and mode[:1] in ("w", "x"):
                    target, what = n.args[0], f"open(.., {mode!r})"
            elif name in _NP_SAVERS and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id in ("np", "numpy") and n.args \
                    and not isinstance(n.args[0], ast.Name):
                # np.save("path", ...) with a direct path; np.savez(f)
                # into an open()-produced handle is the open's business
                target, what = n.args[0], f"np.{name}(..)"
            if target is None:
                continue
            if has_replace or _expr_mentions_tmp(target):
                continue
            findings.append(Finding(path, n.lineno, "GR005", "error",
                f"{_qual(key)}() writes durably via {what} without the "
                f"tmp + os.replace dance — a torn write publishes a "
                f"corrupt file"))
    return _apply_justified(findings, lines)


# ---------------------------------------------------------------------------
# the repo-wide static ownership inventory (lifetrace's ground truth)
# ---------------------------------------------------------------------------

_INVENTORY_OPS = _ACQUIRE_METHODS | {"release", "free_slot"}


class OwnershipInventory:
    """Every function span in the scanned roots that touches the
    allocator vocabulary, in SPAN units (function start..end line): the
    runtime tracer attributes each observed acquire/release callsite to
    a span, and a callsite outside every span is an analyzer blind
    spot."""

    def __init__(self):
        self.spans: List[Dict] = []

    def add_span(self, path: str, qualname: str, start: int, end: int,
                 ops: List[Tuple[str, int]]) -> None:
        self.spans.append({"path": path, "qualname": qualname,
                           "start": int(start), "end": int(end),
                           "ops": [(o, int(ln)) for o, ln in ops]})

    def attributes_callsite(self, path: str, line: int) -> bool:
        return any(s["path"] == path and s["start"] <= line <= s["end"]
                   for s in self.spans)

    def op_count(self) -> int:
        return sum(len(s["ops"]) for s in self.spans)

    def as_dict(self) -> Dict:
        return {"spans": [dict(s) for s in self.spans],
                "ops": self.op_count()}


def static_ownership_inventory(
        repo_root: str,
        roots: Sequence[str] = ("deeplearning4j_tpu",)
) -> OwnershipInventory:
    """Scan ``roots`` for functions touching the allocator vocabulary.
    The tracer's contract: every observed acquire/release callsite must
    fall inside one of these spans."""
    inv = OwnershipInventory()
    for rel in iter_py_files(roots, repo_root):
        full = os.path.join(repo_root, rel)
        try:
            with open(full, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            ops: List[Tuple[str, int]] = []
            for n in ast.walk(node):
                if isinstance(n, ast.Call):
                    name = _call_name(n)
                    if name in _ACQUIRE_METHODS or _is_release_call(n):
                        ops.append((name, n.lineno))
            if ops:
                inv.add_span(rel, node.name, node.lineno,
                             getattr(node, "end_lineno", node.lineno),
                             ops)
    return inv
