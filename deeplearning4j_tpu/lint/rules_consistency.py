"""graftlint consistency rules — checks against the LIVE registries.

GL006  GRAPH_OPS-vs-declarable-registry shadowing (VERDICT round 5, item 4)
GL008  README surface-count drift (VERDICT round 5, items 5/8)

Unlike the AST rules these import the package (and therefore jax), so they
only run in repo mode — ``lint_source`` fixtures never touch them. Callers
must pin JAX_PLATFORMS=cpu (the Makefile/gate do) so importing the package
can never block on an unreachable TPU — exactly the footgun GL002 polices.
"""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, List, Tuple

from deeplearning4j_tpu.lint.core import Finding

CONSISTENCY_RULES: Dict[str, Tuple[Callable[[str], List[Finding]], str]] = {}


def consistency_rule(rule_id: str, description: str):
    def wrap(fn):
        CONSISTENCY_RULES[rule_id] = (fn, description)
        fn.rule_id = rule_id
        fn.description = description
        return fn

    return wrap


def _grep_line(path: str, needle: str) -> int:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for i, text in enumerate(fh, start=1):
                if needle in text:
                    return i
    except OSError:
        pass
    return 1


@consistency_rule("GL006", "GRAPH_OPS silently shadows a declarable-registry op")
def rule_registry_shadowing(repo_root: str) -> List[Finding]:
    """Every GRAPH_OPS key that duplicates a registry op must sit on the
    explicit REGISTRY_SHADOW_WHITELIST — and the whitelist must carry no
    stale entries, so it only ever shrinks with the debt."""
    # importers mutate GRAPH_OPS at import time (identity, tf_* helpers);
    # settle the full surface before comparing
    import deeplearning4j_tpu.imports.keras_import   # noqa: F401
    import deeplearning4j_tpu.imports.onnx_import    # noqa: F401
    import deeplearning4j_tpu.imports.tf_import      # noqa: F401
    from deeplearning4j_tpu.autodiff.samediff import (
        GRAPH_OPS, REGISTRY_SHADOW_WHITELIST)
    from deeplearning4j_tpu.ops.registry import registry

    sd_path = "deeplearning4j_tpu/autodiff/samediff.py"
    abs_sd = os.path.join(repo_root, sd_path)
    shadowed = set(GRAPH_OPS) & set(registry().names())
    findings: List[Finding] = []
    for name in sorted(shadowed - REGISTRY_SHADOW_WHITELIST):
        findings.append(Finding(
            path=sd_path, line=_grep_line(abs_sd, "GRAPH_OPS: Dict"),
            rule="GL006", severity="error",
            message=f"GRAPH_OPS['{name}'] silently shadows registry op "
                    f"'{name}' (resolution: local -> GRAPH_OPS -> registry);"
                    f" add to REGISTRY_SHADOW_WHITELIST with a justification"
                    f" or delete the duplicate"))
    for name in sorted(REGISTRY_SHADOW_WHITELIST - shadowed):
        findings.append(Finding(
            path=sd_path, line=_grep_line(abs_sd, "REGISTRY_SHADOW_WHITELIST"),
            rule="GL006", severity="error",
            message=f"stale whitelist entry '{name}': no longer shadowed — "
                    f"remove it so the whitelist only shrinks"))
    return findings


# (claim regex, live-surface key, human label) — add a pattern here whenever
# README grows a new numeric surface claim
_CLAIM_PATTERNS = [
    (re.compile(r"(\d+)-entry named declarable-op registry"), "registry",
     "declarable-op registry"),
    (re.compile(r"any of the (\d+) catalog ops"), "registry",
     "SameDiff op catalog"),
    (re.compile(r"TF frozen graphs \((\d+) ops"), "tf", "TF op mappers"),
    (re.compile(r"ONNX \((\d+) ops"), "onnx", "ONNX op mappers"),
    (re.compile(r"Keras \((\d+) layer classes"), "keras",
     "Keras layer mappers"),
]


def live_surface_counts() -> Dict[str, int]:
    """The four public surfaces README makes numeric claims about."""
    from deeplearning4j_tpu.imports.keras_import import KerasLayerMapper
    from deeplearning4j_tpu.imports.onnx_import import ONNX_OP_MAPPERS
    from deeplearning4j_tpu.imports.tf_import import TF_OP_MAPPERS
    from deeplearning4j_tpu.ops.registry import registry

    return {"tf": len(TF_OP_MAPPERS),
            "onnx": len(ONNX_OP_MAPPERS),
            "keras": len(KerasLayerMapper.MAPPERS),
            "registry": len(registry().names())}


@consistency_rule("GL008", "README surface count drifted from the live registry")
def rule_readme_counts(repo_root: str) -> List[Finding]:
    readme = os.path.join(repo_root, "README.md")
    if not os.path.exists(readme):
        return []
    live = live_surface_counts()
    findings: List[Finding] = []
    with open(readme, "r", encoding="utf-8") as fh:
        for lineno, text in enumerate(fh, start=1):
            for pat, key, label in _CLAIM_PATTERNS:
                for m in pat.finditer(text):
                    claimed = int(m.group(1))
                    if claimed != live[key]:
                        findings.append(Finding(
                            path="README.md", line=lineno, rule="GL008",
                            severity="error",
                            message=f"README claims {claimed} for {label} "
                                    f"but the live registry has {live[key]};"
                                    f" update the claim (counts are part of "
                                    f"the public surface)"))
    return findings


def run_consistency(repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rule_id, (fn, _desc) in sorted(CONSISTENCY_RULES.items()):
        findings.extend(fn(repo_root))
    return sorted(findings)
