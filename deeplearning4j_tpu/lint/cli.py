"""graftlint CLI.

    python -m deeplearning4j_tpu.lint [paths...] [options]
    python tools/graftlint.py          # identical thin wrapper

Options:
    --baseline PATH    baseline file (default: <repo>/lint_baseline.json)
    --write-baseline   regenerate the baseline from the current findings
                       (shrink-only: findings not already grandfathered are
                       REFUSED and exit 1 — see --allow-growth)
    --allow-growth     allow --write-baseline to add new keys/counts (only
                       for onboarding a brand-new rule)
    --json             emit exactly ONE machine-readable JSON summary line
                       (the driver-artifact contract tools/gate.py relies on)
    --no-consistency   AST rules only (skip registry-loading rules — for
                       environments without jax)
    --rules CSV        run only the named AST rules (e.g. GS001,GS002 —
                       `make shape-lint` uses this to run the graftshape
                       tier alone); implies --no-consistency unless a
                       consistency rule id is in the list
    --list-rules       print the rule catalog and exit

Exit code 0 iff there are no findings beyond the grandfathered baseline.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from deeplearning4j_tpu.lint.core import (
    AST_RULES, Finding, lint_paths, run_baselined_cli)

DEFAULT_ROOTS = ("deeplearning4j_tpu", "tools", "examples")


def find_repo_root(start: Optional[str] = None) -> str:
    """Walk up from this file to the directory holding the package — the
    lint paths and baseline are repo-relative."""
    here = start or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return here


def run(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=None)
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--allow-growth", action="store_true",
                    help="let --write-baseline add NEW keys/counts (only "
                         "for onboarding a brand-new rule; the default "
                         "refuses growth so regenerating can never "
                         "grandfather a regression)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--no-consistency", action="store_true")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    subset = bool(args.paths)
    if subset and args.write_baseline and not args.baseline:
        # a subset scan misses every baselined finding outside the subset;
        # writing it over the repo-wide baseline would make the next full
        # run report all of those as NEW
        ap.error("--write-baseline with explicit paths would overwrite the "
                 "repo-wide baseline with a subset scan; pass --baseline "
                 "to write elsewhere or drop the path arguments")

    if args.list_rules:
        from deeplearning4j_tpu.lint.rules_consistency import CONSISTENCY_RULES
        for rid, (_fn, desc) in sorted({**AST_RULES, **CONSISTENCY_RULES}.items()):
            print(f"{rid}  {desc}")
        return 0

    repo_root = find_repo_root()
    roots = list(args.paths) if args.paths else list(DEFAULT_ROOTS)
    baseline_path = args.baseline or os.path.join(repo_root,
                                                  "lint_baseline.json")

    rule_filter = None
    if args.rules:
        rule_filter = tuple(r.strip() for r in args.rules.split(",")
                            if r.strip())
        unknown = [r for r in rule_filter if r not in AST_RULES]
        try:
            from deeplearning4j_tpu.lint.rules_consistency import (
                CONSISTENCY_RULES)
            unknown = [r for r in unknown if r not in CONSISTENCY_RULES]
        except ImportError:
            pass
        if unknown:
            ap.error(f"unknown rule id(s): {', '.join(unknown)} "
                     "(see --list-rules)")

    findings: List[Finding] = lint_paths(
        roots, repo_root,
        rules=[r for r in rule_filter if r in AST_RULES]
        if rule_filter else None)
    if rule_filter is not None:
        # a rule-filtered scan cannot see the other rules' findings, so the
        # consistency tier only runs when one of ITS ids was asked for
        run_cons = (not args.no_consistency and any(
            r not in AST_RULES for r in rule_filter))
    else:
        run_cons = not args.no_consistency
    if run_cons:
        # the consistency rules load the live registries (and thus jax);
        # pin the CPU backend so lint can NEVER hang on an unreachable TPU
        # (the ambient sitecustomize pins the platform at startup, so the
        # env var alone is not enough — conftest.py has the same dance)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except ImportError:
            pass
        from deeplearning4j_tpu.lint.rules_consistency import run_consistency
        cons = run_consistency(repo_root)
        if rule_filter is not None:
            cons = [f for f in cons if f.rule in rule_filter]
        findings.extend(cons)
    findings.sort()

    # shared baseline-CLI tail (lint/core.py — also drives graftcheck):
    # --write-baseline shrink-only flow, or diff + one-JSON-line contract
    return run_baselined_cli(
        "graftlint", findings, baseline_path,
        write=args.write_baseline, allow_growth=args.allow_growth,
        json_mode=args.json,
        # a subset scan cannot tell "fixed" from "outside the paths", and a
        # rule-filtered scan cannot tell "fixed" from "rule not run"
        suppress_fixed=subset or rule_filter is not None,
        fail_hint="fix the new findings above or (only with a written "
                  "justification) add a 'graftlint: disable=<RULE>' "
                  "comment")


def main() -> None:
    sys.exit(run())
