"""Multi-host bootstrap + per-host data sharding.

Reference parity (SURVEY §4.4, §6.8):
  * SparkDl4jMultiLayer / SharedTrainingMaster driver-executor bootstrap:
    Spark RPC broadcasts config + initial params; Aeron mesh forms for
    gradient exchange; VirtualDataSetIterator partitions data per executor.

TPU-native realization: ``jax.distributed.initialize`` (coordination service
= the driver/parameter-server bootstrap role; rank assignment + barrier),
after which every host runs the SAME SPMD program over the global mesh —
gradient exchange is inside the compiled step (ICI/DCN collectives), not a
transport we operate. Data: deterministic per-host shard assignment
(host_id → slice of files/examples), the VirtualDataSetIterator role.

In this 1-chip environment multi-host paths are exercised via
multi-process CPU tests (SURVEY §5.5 translation).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize wrapper; env-var driven when args absent
    (DL4J_TPU_COORDINATOR / DL4J_TPU_NUM_PROCS / DL4J_TPU_PROC_ID)."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None and "DL4J_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["DL4J_TPU_NUM_PROCS"])
    if process_id is None and "DL4J_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["DL4J_TPU_PROC_ID"])
    if coordinator_address is None:
        return  # single-process run; nothing to do
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def host_shard(items: Sequence, process_id: Optional[int] = None,
               num_processes: Optional[int] = None) -> list:
    """Deterministic per-host shard of a work list (files, example ranges) —
    the VirtualDataSetIterator partitioning role. host i takes items[i::N]."""
    import jax

    pid = process_id if process_id is not None else jax.process_index()
    n = num_processes if num_processes is not None else jax.process_count()
    return list(items)[pid::n]


class ShardedDataSetIterator:
    """Wrap a host-local iterator so each host sees its deterministic shard
    of batches (batch-level round-robin)."""

    def __init__(self, base, process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        import jax

        self.base = base
        self.pid = process_id if process_id is not None else jax.process_index()
        self.n = num_processes if num_processes is not None else jax.process_count()

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self.base.reset()

    def __iter__(self):
        for i, ds in enumerate(self.base):
            if i % self.n == self.pid:
                yield ds


# ---------------------------------------------------------------------------
# Multi-process launcher CLI (round 4) — the SharedTrainingMaster JOB role
# (SURVEY §4.4, §8.2-M5): spawn N worker processes that form a
# jax.distributed cluster, stream their output, and on worker failure kill
# the survivors and relaunch the whole job (checkpoint-restart elasticity,
# SURVEY §6.3 — workers resume from their latest checkpoint on restart).
#
#   python -m deeplearning4j_tpu.parallel.launch --nprocs 2 --restarts 1 \
#       -- my_fit_script.py arg1 arg2
# ---------------------------------------------------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch(nprocs: int, argv: Sequence[str], restarts: int = 0,
           env_extra: Optional[dict] = None, timeout: float = 600.0) -> int:
    """Run ``argv`` as ``nprocs`` coordinated worker processes.

    Returns the exit code (0 = all workers succeeded on some attempt).
    Each attempt uses a fresh coordinator port; workers read the cluster
    layout from DL4J_TPU_* env vars via initialize_distributed()."""
    import subprocess
    import sys
    import time

    for attempt in range(restarts + 1):
        port = _free_port()
        procs = []
        for pid in range(nprocs):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update({
                "DL4J_TPU_COORDINATOR": f"127.0.0.1:{port}",
                "DL4J_TPU_NUM_PROCS": str(nprocs),
                "DL4J_TPU_PROC_ID": str(pid),
            })
            procs.append(subprocess.Popen(
                [sys.executable] + list(argv), env=env))
        deadline = time.time() + timeout
        failed = timed_out = False
        while procs:
            for p in list(procs):
                rc = p.poll()
                if rc is None:
                    continue
                procs.remove(p)
                if rc != 0:
                    failed = True
            timed_out = bool(procs) and time.time() > deadline
            if failed or timed_out:
                for p in procs:  # kill survivors (they may be blocked in a
                    p.terminate()  # collective waiting on the dead rank)
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                break
            time.sleep(0.1)
        if not failed and not timed_out and procs == []:
            return 0
        # a timeout is a healthy-but-slow job, not a crash: report it
        # distinctly and do not burn a restart attempt on it (ADVICE r4 #5)
        if timed_out and not failed:
            print(f"[launch] attempt {attempt + 1}: workers exceeded the "
                  f"--timeout of {timeout:.0f}s and were killed (not a "
                  f"worker failure; raise --timeout for long jobs)",
                  flush=True)
            return 124  # conventional timeout exit code
        print(f"[launch] attempt {attempt + 1}/{restarts + 1} failed "
              f"(worker crash)"
              + ("; relaunching (workers resume from checkpoint)"
                 if attempt < restarts else ""),
              flush=True)
    return 1


def main(args: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.parallel.launch",
        description="Multi-process training launcher (SharedTrainingMaster "
                    "job role): coordinates N workers via jax.distributed; "
                    "on failure relaunches so workers resume from their "
                    "latest checkpoint.")
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--restarts", type=int, default=0,
                    help="relaunch attempts after a worker failure")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-attempt wall-clock limit (seconds)")
    ap.add_argument("argv", nargs="+",
                    help="worker script and its args (prefix with --)")
    ns = ap.parse_args(args)
    return launch(ns.nprocs, ns.argv, restarts=ns.restarts,
                  timeout=ns.timeout)


if __name__ == "__main__":
    raise SystemExit(main())
