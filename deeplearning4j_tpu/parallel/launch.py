"""Multi-host bootstrap + per-host data sharding.

Reference parity (SURVEY §4.4, §6.8):
  * SparkDl4jMultiLayer / SharedTrainingMaster driver-executor bootstrap:
    Spark RPC broadcasts config + initial params; Aeron mesh forms for
    gradient exchange; VirtualDataSetIterator partitions data per executor.

TPU-native realization: ``jax.distributed.initialize`` (coordination service
= the driver/parameter-server bootstrap role; rank assignment + barrier),
after which every host runs the SAME SPMD program over the global mesh —
gradient exchange is inside the compiled step (ICI/DCN collectives), not a
transport we operate. Data: deterministic per-host shard assignment
(host_id → slice of files/examples), the VirtualDataSetIterator role.

In this 1-chip environment multi-host paths are exercised via
multi-process CPU tests (SURVEY §5.5 translation).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize wrapper; env-var driven when args absent
    (DL4J_TPU_COORDINATOR / DL4J_TPU_NUM_PROCS / DL4J_TPU_PROC_ID)."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None and "DL4J_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["DL4J_TPU_NUM_PROCS"])
    if process_id is None and "DL4J_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["DL4J_TPU_PROC_ID"])
    if coordinator_address is None:
        return  # single-process run; nothing to do
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def host_shard(items: Sequence, process_id: Optional[int] = None,
               num_processes: Optional[int] = None) -> list:
    """Deterministic per-host shard of a work list (files, example ranges) —
    the VirtualDataSetIterator partitioning role. host i takes items[i::N]."""
    import jax

    pid = process_id if process_id is not None else jax.process_index()
    n = num_processes if num_processes is not None else jax.process_count()
    return list(items)[pid::n]


class ShardedDataSetIterator:
    """Per-host shard of a dataset iterator.

    When the base iterator supports FILE-level sharding (``shard_files()``,
    e.g. ImageRecordReader), it is sharded ONCE at construction and then
    iterated fully — each host reads/decodes only its 1/N of the data.
    Otherwise this falls back to batch round-robin, which still iterates
    (and pays ETL for) the FULL base on every host — correct but O(global)
    per host; a one-time warning says so (round-4 verdict weak #4)."""

    def __init__(self, base, process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        import jax

        self.base = base
        self.pid = process_id if process_id is not None else jax.process_index()
        self.n = num_processes if num_processes is not None else jax.process_count()
        self._file_sharded = False
        if hasattr(base, "shard_files") and self.n > 1:
            if getattr(base, "_dl4j_file_sharded", False):
                raise ValueError(
                    "this reader was already file-sharded by another "
                    "ShardedDataSetIterator — wrapping it twice would "
                    "compound to 1/N² of the data; reuse the first wrapper "
                    "or construct a fresh reader")
            base.shard_files(self.pid, self.n)
            base._dl4j_file_sharded = True
            self._file_sharded = True
        elif self.n > 1:
            import warnings

            warnings.warn(
                "ShardedDataSetIterator: base iterator has no shard_files();"
                " falling back to batch round-robin — every host still runs"
                " the full ETL. Give the reader file-level sharding for"
                " O(global/N) input cost.", stacklevel=2)

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self.base.reset()

    def __iter__(self):
        if self._file_sharded:
            yield from self.base
            return
        for i, ds in enumerate(self.base):
            if i % self.n == self.pid:
                yield ds


# ---------------------------------------------------------------------------
# Multi-process launcher CLI (round 4) — the SharedTrainingMaster JOB role
# (SURVEY §4.4, §8.2-M5): spawn N worker processes that form a
# jax.distributed cluster, stream their output, and on worker failure kill
# the survivors and relaunch the whole job (checkpoint-restart elasticity,
# SURVEY §6.3 — workers resume from their latest checkpoint on restart).
#
#   python -m deeplearning4j_tpu.parallel.launch --nprocs 2 --restarts 1 \
#       -- my_fit_script.py arg1 arg2
# ---------------------------------------------------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch(nprocs: int, argv: Sequence[str], restarts: int = 0,
           env_extra: Optional[dict] = None, timeout: float = 600.0) -> int:
    """Run ``argv`` as ``nprocs`` coordinated worker processes.

    Returns the exit code (0 = all workers succeeded on some attempt).
    Each attempt uses a fresh coordinator port; workers read the cluster
    layout from DL4J_TPU_* env vars via initialize_distributed()."""
    import subprocess
    import sys
    import time

    for attempt in range(restarts + 1):
        port = _free_port()
        procs = []
        for pid in range(nprocs):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update({
                "DL4J_TPU_COORDINATOR": f"127.0.0.1:{port}",
                "DL4J_TPU_NUM_PROCS": str(nprocs),
                "DL4J_TPU_PROC_ID": str(pid),
            })
            procs.append(subprocess.Popen(
                [sys.executable] + list(argv), env=env))
        deadline = time.time() + timeout
        failed = timed_out = False
        while procs:
            for p in list(procs):
                rc = p.poll()
                if rc is None:
                    continue
                procs.remove(p)
                if rc != 0:
                    failed = True
            timed_out = bool(procs) and time.time() > deadline
            if failed or timed_out:
                for p in procs:  # kill survivors (they may be blocked in a
                    p.terminate()  # collective waiting on the dead rank)
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                break
            time.sleep(0.1)
        if not failed and not timed_out and procs == []:
            return 0
        # a timeout is a healthy-but-slow job, not a crash: report it
        # distinctly and do not burn a restart attempt on it (ADVICE r4 #5)
        if timed_out and not failed:
            print(f"[launch] attempt {attempt + 1}: workers exceeded the "
                  f"--timeout of {timeout:.0f}s and were killed (not a "
                  f"worker failure; raise --timeout for long jobs)",
                  flush=True)
            return 124  # conventional timeout exit code
        print(f"[launch] attempt {attempt + 1}/{restarts + 1} failed "
              f"(worker crash)"
              + ("; relaunching (workers resume from checkpoint)"
                 if attempt < restarts else ""),
              flush=True)
    return 1


def main(args: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.parallel.launch",
        description="Multi-process training launcher (SharedTrainingMaster "
                    "job role): coordinates N workers via jax.distributed; "
                    "on failure relaunches so workers resume from their "
                    "latest checkpoint.")
    ap.add_argument("--nprocs", type=int, required=True)
    ap.add_argument("--restarts", type=int, default=0,
                    help="relaunch attempts after a worker failure")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-attempt wall-clock limit (seconds)")
    ap.add_argument("argv", nargs="+",
                    help="worker script and its args (prefix with --)")
    ns = ap.parse_args(args)
    return launch(ns.nprocs, ns.argv, restarts=ns.restarts,
                  timeout=ns.timeout)


if __name__ == "__main__":
    raise SystemExit(main())


def distributed_evaluate(net, iterator, evaluation=None):
    """Cluster-wide evaluation (the dl4j-spark RDD ``doEvaluation`` role,
    SURVEY §3.3): every process evaluates ITS shard of ``iterator``
    (typically a ShardedDataSetIterator), then the per-process Evaluation
    states merge across the jax.distributed cluster — counts are summed via
    an all-gather of the confusion matrix, so every rank returns the same
    global Evaluation. Single-process runs degrade to plain evaluate()."""
    import jax

    local = net.evaluate(iterator, evaluation=evaluation)
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils

    # EVERY rank must execute the SAME collectives in the same order (a
    # zero-batch rank running a different sequence would deadlock the
    # cluster): first agree on num_classes, then gather fixed-shape
    # confusion matrices (zero-padded on ranks that saw fewer classes /
    # no batches).
    local_n = 0 if local.num_classes is None else int(local.num_classes)
    n = int(multihost_utils.process_allgather(np.asarray(local_n)).max())
    conf = np.zeros((n, n), np.int64)
    if local.confusion is not None:
        ln = local.confusion.shape[0]
        conf[:ln, :ln] = local.confusion
    gathered = multihost_utils.process_allgather(conf)
    local.num_classes = n
    local.confusion = np.asarray(gathered).sum(axis=0)
    return local
