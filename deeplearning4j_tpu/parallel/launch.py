"""Multi-host bootstrap + per-host data sharding.

Reference parity (SURVEY §4.4, §6.8):
  * SparkDl4jMultiLayer / SharedTrainingMaster driver-executor bootstrap:
    Spark RPC broadcasts config + initial params; Aeron mesh forms for
    gradient exchange; VirtualDataSetIterator partitions data per executor.

TPU-native realization: ``jax.distributed.initialize`` (coordination service
= the driver/parameter-server bootstrap role; rank assignment + barrier),
after which every host runs the SAME SPMD program over the global mesh —
gradient exchange is inside the compiled step (ICI/DCN collectives), not a
transport we operate. Data: deterministic per-host shard assignment
(host_id → slice of files/examples), the VirtualDataSetIterator role.

In this 1-chip environment multi-host paths are exercised via
multi-process CPU tests (SURVEY §5.5 translation).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize wrapper; env-var driven when args absent
    (DL4J_TPU_COORDINATOR / DL4J_TPU_NUM_PROCS / DL4J_TPU_PROC_ID)."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None and "DL4J_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["DL4J_TPU_NUM_PROCS"])
    if process_id is None and "DL4J_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["DL4J_TPU_PROC_ID"])
    if coordinator_address is None:
        return  # single-process run; nothing to do
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def host_shard(items: Sequence, process_id: Optional[int] = None,
               num_processes: Optional[int] = None) -> list:
    """Deterministic per-host shard of a work list (files, example ranges) —
    the VirtualDataSetIterator partitioning role. host i takes items[i::N]."""
    import jax

    pid = process_id if process_id is not None else jax.process_index()
    n = num_processes if num_processes is not None else jax.process_count()
    return list(items)[pid::n]


class ShardedDataSetIterator:
    """Wrap a host-local iterator so each host sees its deterministic shard
    of batches (batch-level round-robin)."""

    def __init__(self, base, process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        import jax

        self.base = base
        self.pid = process_id if process_id is not None else jax.process_index()
        self.n = num_processes if num_processes is not None else jax.process_count()

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self.base.reset()

    def __iter__(self):
        for i, ds in enumerate(self.base):
            if i % self.n == self.pid:
                yield ds
