"""TrainingSupervisor — preemption-proof fit (docs/ROBUSTNESS.md).

The training-side twin of the serving engine supervisor: where that one
turns worker death into bounded restarts with request re-admission, this
one turns a killed fit — injected ``preemption`` fault, TPU pod
preemption, any crash between step dispatches — into a bounded
restore-and-resume whose trajectory is BIT-EXACT against the
uninterrupted run:

* every restart restores the full training state from the newest intact
  checkpoint (params + updater slots + RNG key + step/epoch + data
  cursor), so the replayed steps consume exactly the batches and RNG
  splits the oracle would have;
* the net object (and its ``_jit_cache``) survives in-process restarts,
  and the restored arrays keep their shapes/dtypes — resume pays ZERO
  ``new_shape`` recompiles, exactly as serving recovery does;
* a SIGTERM (the real pod-preemption notice) flips the graceful flag in
  ``faults``: the fit loop takes one final synchronous snapshot and
  exits cleanly inside the grace period, and the next launch resumes
  from that exact step.

Usage::

    net = MultiLayerNetwork(conf).init()
    ckpt = TrainingCheckpointer(dir, use_orbax=False)
    sup = TrainingSupervisor(net, ckpt, save_every=10, install_sigterm=True)
    sup.fit(features, labels, epochs=3, batch_size=32)   # resumable
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Any, Optional

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.parallel.checkpoint import (
    CheckpointTrainingListener,
    TrainingCheckpointer,
)

logger = logging.getLogger(__name__)


class TrainingSupervisor:
    """Supervise a fit loop: periodic async checkpoints, bounded
    restore-and-resume on crashes, graceful SIGTERM snapshots.

    Mirrors the engine supervisor's shape: ``max_restarts`` caps recovery
    attempts (the budget spent -> the original exception propagates),
    restarts back off exponentially from ``restart_backoff_s``, every
    resume is counted (``dl4j_tpu_ckpt_resumes_total``) and logged
    (``train_resume`` JSONL). ``fit`` returns ``"completed"`` or
    ``"preempted"`` (graceful SIGTERM exit — relaunch to continue).
    """

    def __init__(self, net, checkpointer: TrainingCheckpointer, *,
                 save_every: int = 1, max_restarts: int = 5,
                 restart_backoff_s: float = 0.05,
                 install_sigterm: bool = False,
                 asynchronous: bool = True):
        self.net = net
        self.ckpt = checkpointer
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.install_sigterm = install_sigterm
        self.restarts = 0
        self.listener = CheckpointTrainingListener(
            checkpointer, every_n_iterations=save_every,
            asynchronous=asynchronous)
        self._prev_handler: Any = None

    # ----------------------------------------------------------- sigterm
    def _install_handler(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            logger.warning("SIGTERM handler not installed — fit is not on "
                           "the main thread")
            return
        def _on_sigterm(signum, frame):
            faults.request_preemption()
        self._prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)

    def _uninstall_handler(self) -> None:
        if self._prev_handler is not None:
            signal.signal(signal.SIGTERM, self._prev_handler)
            self._prev_handler = None

    # --------------------------------------------------------------- fit
    def _attach(self) -> None:
        listeners = getattr(self.net, "listeners", None)
        if listeners is None:  # SameDiff keeps them in _listeners
            listeners = getattr(self.net, "_listeners", None)
            if listeners is None:
                listeners = []
                self.net._listeners = listeners
        if self.listener not in listeners:
            listeners.append(self.listener)

    def resume(self) -> Optional[int]:
        """Restore the newest intact checkpoint into the net (drains the
        async queue first). Returns the restored step or None."""
        self.ckpt.wait_until_finished(timeout=60.0)
        restored = self.ckpt.restore(self.net)
        # cold-start restore of the COMPILED state too: with
        # $DL4J_TPU_COMPILE_CACHE set, any train step exported by a prior
        # process (autodiff/export.py export_train_step) deserializes into
        # the net's _jit_cache here — the resumed fit's first batch runs
        # the restored executable (ledger: cache_hit) instead of re-jitting
        from deeplearning4j_tpu.autodiff import export as _aot_export

        _aot_export.maybe_warm_boot_net(self.net)
        if restored is not None:
            observe.metrics().counter("dl4j_tpu_ckpt_resumes_total").inc()
            observe.log_event(
                "train_resume", step=restored, restarts=self.restarts,
                epoch=int(getattr(self.net, "epoch_count", 0)),
                cursor=int(getattr(self.net, "batch_in_epoch", 0)))
            logger.warning(
                "training resumed from checkpoint step %d (epoch %d, "
                "cursor %d)", restored,
                int(getattr(self.net, "epoch_count", 0)),
                int(getattr(self.net, "batch_in_epoch", 0)))
        return restored

    def _realign_iterator(self, data) -> None:
        """A shuffling ListDataSetIterator keys its per-epoch order on an
        internal epoch counter — realign it with the net's restored epoch
        so the replayed remainder sees the oracle's batch order."""
        if hasattr(data, "_epoch"):
            data._epoch = int(getattr(self.net, "epoch_count", 0))

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 32, resume: bool = True,
            **fit_kwargs) -> str:
        """Run (or resume) a supervised fit to ``epochs`` total epochs.

        ``epochs`` counts from the net's zero state: a resumed net with
        ``epoch_count == 2`` and ``epochs=5`` trains 3 more. The data is
        normalized ONCE so every restart replays the identical batch
        sequence (arrays -> a deterministic ListDataSetIterator)."""
        from deeplearning4j_tpu.datasets.dataset import (
            DataSet, ListDataSetIterator)

        if labels is not None:
            data = ListDataSetIterator(DataSet(data, labels),
                                       batch_size=batch_size)
        elif isinstance(data, DataSet):
            data = ListDataSetIterator(data, batch_size=batch_size)

        self._attach()
        if self.install_sigterm:
            self._install_handler()

        def preempted() -> str:
            # a supervisor that installed the SIGTERM handler OWNS the
            # flag: clear it so a later fit in a surviving process can
            # train (an externally-requested preemption stays set — its
            # requester clears it)
            if self.install_sigterm:
                faults.clear_preemption()
            return "preempted"

        try:
            if resume and self.ckpt.latest_step() is not None:
                self.resume()
            while True:
                if faults.preemption_requested():
                    return preempted()
                remaining = epochs - int(getattr(self.net, "epoch_count", 0))
                if remaining <= 0:
                    return "completed"
                self._realign_iterator(data)
                epoch_before = int(getattr(self.net, "epoch_count", 0))
                try:
                    self.net.fit(data, epochs=remaining, **fit_kwargs)
                except Exception as e:
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        logger.error(
                            "training crashed %d times (cap %d) — giving "
                            "up: %r", self.restarts, self.max_restarts, e)
                        raise
                    backoff = min(
                        self.restart_backoff_s * (2 ** (self.restarts - 1)),
                        2.0)
                    logger.warning(
                        "training crashed (%r) — restart %d/%d after "
                        "%.3fs backoff", e, self.restarts,
                        self.max_restarts, backoff)
                    time.sleep(backoff)
                    self.resume()
                    continue
                if faults.preemption_requested():
                    # the fit loop snapshotted and exited cleanly
                    return preempted()
                if int(getattr(self.net, "epoch_count",
                               epoch_before)) == epoch_before:
                    # no progress and no exception (empty data?) — a loop
                    # here would spin forever
                    return "completed"
        finally:
            self._uninstall_handler()
            self.ckpt.wait_until_finished(timeout=60.0)
