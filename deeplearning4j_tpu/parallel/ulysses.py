"""Ulysses-style all-to-all sequence parallelism.

The second first-class long-context strategy next to ring attention
(parallel/ring_attention.py). Where the ring rotates K/V shards and keeps an
online-softmax accumulator, Ulysses (DeepSpeed-Ulysses, Jacobs et al. 2023)
re-shards with two all-to-alls: activations enter sharded over SEQUENCE,
an all-to-all re-shards attention inputs over HEADS (each device then holds
its heads' FULL sequence and runs ordinary dense/flash attention), and a
second all-to-all restores sequence sharding afterwards.

Trade-offs vs the ring (why both exist, as in the reference ecosystem):
  * comm volume: Ulysses moves q,k,v,out once each (4·T/N·D per device per
    layer) regardless of N; the ring moves k,v N−1 times.
  * constraint: Ulysses needs num_heads % N == 0; the ring has no head
    constraint but serializes N hops.
On TPU both ride ICI as XLA collectives: ``all_to_all`` here, ``ppermute``
there — never hand-written transports (SURVEY §3.5 comm-backend row).

Usage (inputs sharded (B, H, T/N, D) over axis 'seq'):
    out = ulysses_attention(q, k, v, mesh=mesh, axis='seq')
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _ulysses_local(q, k, v, *, axis_name: str, scale: float, causal: bool):
    """Per-shard body (under shard_map). q/k/v: (B, H, T_local, D) — the
    LOCAL sequence shard of all heads. Re-shards to all heads' full
    sequence for H/N local heads, attends densely, re-shards back."""
    def seq_to_heads(x):
        # (B, H, T/N, D) -> (B, H/N, T, D): ONE tiled all-to-all — head
        # chunk j goes to device j, and each device concatenates its head
        # chunk from every source along the sequence axis in source
        # (= sequence-shard) order
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):
        # inverse: (B, H/N, T, D) -> (B, H, T/N, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32) * scale,
                   kh.astype(jnp.float32))
    if causal:
        t = s.shape[-1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention(q, k, v, *, mesh: Mesh, axis: str = "seq",
                      scale: Optional[float] = None, causal: bool = False):
    """All-to-all sequence-parallel attention. q/k/v: (B, H, T, D) GLOBAL
    shapes, sharded over T on ``axis``. num_heads must divide by the axis
    size."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    h = q.shape[1]
    if h % n != 0:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the '{axis}' axis "
            f"size ({n}) — use ring_attention for head-indivisible meshes")
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, None, axis, None)
    fn = shard_map(
        lambda a, b, c: _ulysses_local(a, b, c, axis_name=axis, scale=sc,
                                       causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
