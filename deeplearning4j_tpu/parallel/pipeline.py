"""Pipeline parallelism — GPipe-style microbatched stage execution over a
``pipe`` mesh axis.

Reference parity: the reference scales only by data parallelism (Spark
TrainingMaster) — pipeline parallelism is an EXCEEDS-reference capability
the TPU build needs to claim the same scale story modern frameworks have
(SURVEY §6.7's long-context/parallelism mandate; the driver's multichip
contract names tp/pp/dp/sp/ep shardings).

TPU-native realization (scaling-book recipe): every device holds ONE
stage's parameters (params stacked on the leading axis, sharded over
``pipe``); a ``shard_map`` runs the classic GPipe schedule — a lax.scan
over (microbatches + stages - 1) ticks where each tick applies the local
stage to its current activation and ``ppermute``-shifts activations to the
next stage over ICI. Bubble fraction = (S-1)/(M+S-1), the standard GPipe
cost; raise the microbatch count to amortize.

The stage function must be shape-preserving (same activation shape in and
out), which is the usual transformer-block setting; a head/tail projection
runs outside the pipeline.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of per-stage param pytrees on a new leading axis —
    the layout pipeline_forward shards over the ``pipe`` axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_spec(stacked_params, axis: str = "pipe"):
    """PartitionSpecs placing each stage's slice on its pipe-axis device."""
    return jax.tree.map(
        lambda x: P(axis, *([None] * (np.ndim(x) - 1))), stacked_params)


def pipeline_forward(stage_fn: Callable, mesh: Mesh, *, num_microbatches: int,
                     axis: str = "pipe"):
    """Build a jittable f(stacked_params, x) running ``stage_fn`` as a
    GPipe pipeline over the mesh's ``axis``.

    stage_fn(stage_params, x_microbatch) -> y_microbatch (shape-preserving).
    x: (batch, ...) with batch divisible by num_microbatches. Returns the
    pipeline output in the same layout.

    The schedule: T = M + S - 1 ticks. At tick t, stage s processes
    microbatch (t - s) when 0 <= t - s < M; activations ppermute to s+1
    between ticks. Implemented branch-free: out-of-range ticks process
    garbage that is masked out of the collected outputs, so the whole
    schedule is ONE lax.scan XLA can pipeline.
    """
    n_stages = mesh.shape[axis]

    def per_device(params_slice, x_shard):
        # params_slice: this stage's params (leading axis stripped by
        # shard_map); x_shard: the FULL batch (replicated over pipe).
        stage = jax.lax.axis_index(axis)
        m = num_microbatches
        micro = x_shard.reshape((m, x_shard.shape[0] // m) + x_shard.shape[1:])
        ticks = m + n_stages - 1

        def tick(carry, t):
            act = carry  # activation arriving at THIS stage this tick
            # stage 0 injects microbatch t (when valid); others use carry
            inject = micro[jnp.clip(t, 0, m - 1)]
            x_in = jnp.where(stage == 0, inject, act)
            y = stage_fn(jax.tree.map(lambda p: p[0], params_slice), x_in)
            # shift activations forward one stage over ICI
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            shifted = jax.lax.ppermute(y, axis, perm)
            # the LAST stage's output for microbatch (t - S + 1) is ready
            return shifted, y

        act0 = jnp.zeros_like(micro[0])
        # the carry becomes device-varying after the first ppermute; mark
        # the initial carry varying too (jax>=0.8 VMA checking)
        if hasattr(jax.lax, "pcast"):
            act0 = jax.lax.pcast(act0, (axis,), to="varying")
        elif hasattr(jax.lax, "pvary"):
            act0 = jax.lax.pvary(act0, (axis,))
        _, ys = jax.lax.scan(tick, act0, jnp.arange(ticks))
        # ys[t] = this stage's output at tick t; the final stage emitted
        # microbatch j at tick j + S - 1
        idx = jnp.arange(m) + (n_stages - 1)
        out = ys[idx]  # only meaningful on the last stage
        out = out.reshape((m * out.shape[1],) + out.shape[2:])
        # broadcast the last stage's result to every device (replicated
        # output): zero the other stages' buffers and psum over the axis
        out = jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis)

    def run(stacked_params, x):
        f = shard_map(
            per_device, mesh=mesh,
            in_specs=(pipeline_spec(stacked_params, axis), P()),
            out_specs=P())
        return f(stacked_params, x)

    return run


class PipelineParallelTrainer:
    """Minimal pipeline-parallel trainer: stages of shape-preserving blocks
    + an output head, trained with jax.grad THROUGH the pipeline schedule
    (the scan/ppermute program is differentiable end to end)."""

    def __init__(self, stage_fn: Callable, head_fn: Callable, mesh: Mesh,
                 *, num_microbatches: int, axis: str = "pipe"):
        self.stage_fn = stage_fn
        self.head_fn = head_fn
        self.mesh = mesh
        self.axis = axis
        self.num_microbatches = num_microbatches
        self._fwd = pipeline_forward(stage_fn, mesh,
                                     num_microbatches=num_microbatches,
                                     axis=axis)

    def loss_fn(self, stacked_params, head_params, x, y):
        feats = self._fwd(stacked_params, x)
        return self.head_fn(head_params, feats, y)

    def make_train_step(self, lr: float = 0.1):
        grad_fn = jax.value_and_grad(self.loss_fn, argnums=(0, 1))

        @jax.jit
        def step(stacked_params, head_params, x, y):
            loss, (gs, gh) = grad_fn(stacked_params, head_params, x, y)
            stacked_params = jax.tree.map(lambda p, g: p - lr * g,
                                          stacked_params, gs)
            head_params = jax.tree.map(lambda p, g: p - lr * g,
                                       head_params, gh)
            return stacked_params, head_params, loss

        return step
