"""Pipeline parallelism — GPipe-style microbatched stage execution over a
``pipe`` mesh axis.

Reference parity: the reference scales only by data parallelism (Spark
TrainingMaster) — pipeline parallelism is an EXCEEDS-reference capability
the TPU build needs to claim the same scale story modern frameworks have
(SURVEY §6.7's long-context/parallelism mandate; the driver's multichip
contract names tp/pp/dp/sp/ep shardings).

TPU-native realization (scaling-book recipe): every device holds ONE
stage's parameters (params stacked on the leading axis, sharded over
``pipe``); a ``shard_map`` runs the classic GPipe schedule — a lax.scan
over (microbatches + stages - 1) ticks where each tick applies the local
stage to its current activation and ``ppermute``-shifts activations to the
next stage over ICI. Bubble fraction = (S-1)/(M+S-1), the standard GPipe
cost; raise the microbatch count to amortize.

The stage function must be shape-preserving (same activation shape in and
out), which is the usual transformer-block setting; a head/tail projection
runs outside the pipeline.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of per-stage param pytrees on a new leading axis —
    the layout pipeline_forward shards over the ``pipe`` axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_spec(stacked_params, axis: str = "pipe"):
    """PartitionSpecs placing each stage's slice on its pipe-axis device."""
    return jax.tree.map(
        lambda x: P(axis, *([None] * (np.ndim(x) - 1))), stacked_params)


def pipeline_forward(stage_fn: Callable, mesh: Mesh, *, num_microbatches: int,
                     axis: str = "pipe"):
    """Build a jittable f(stacked_params, x) running ``stage_fn`` as a
    GPipe pipeline over the mesh's ``axis``.

    stage_fn(stage_params, x_microbatch) -> y_microbatch (shape-preserving).
    x: (batch, ...) with batch divisible by num_microbatches. Returns the
    pipeline output in the same layout.

    The schedule: T = M + S - 1 ticks. At tick t, stage s processes
    microbatch (t - s) when 0 <= t - s < M; activations ppermute to s+1
    between ticks. Implemented branch-free: out-of-range ticks process
    garbage that is masked out of the collected outputs, so the whole
    schedule is ONE lax.scan XLA can pipeline.
    """
    n_stages = mesh.shape[axis]

    def per_device(params_slice, x_shard):
        # params_slice: this stage's params (leading axis stripped by
        # shard_map); x_shard: the FULL batch (replicated over pipe).
        stage = jax.lax.axis_index(axis)
        m = num_microbatches
        micro = x_shard.reshape((m, x_shard.shape[0] // m) + x_shard.shape[1:])
        ticks = m + n_stages - 1

        def tick(carry, t):
            act = carry  # activation arriving at THIS stage this tick
            # stage 0 injects microbatch t (when valid); others use carry
            inject = micro[jnp.clip(t, 0, m - 1)]
            x_in = jnp.where(stage == 0, inject, act)
            y = stage_fn(jax.tree.map(lambda p: p[0], params_slice), x_in)
            # shift activations forward one stage over ICI
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            shifted = jax.lax.ppermute(y, axis, perm)
            # the LAST stage's output for microbatch (t - S + 1) is ready
            return shifted, y

        act0 = jnp.zeros_like(micro[0])
        # the carry becomes device-varying after the first ppermute; mark
        # the initial carry varying too (jax>=0.8 VMA checking)
        if hasattr(jax.lax, "pcast"):
            act0 = jax.lax.pcast(act0, (axis,), to="varying")
        elif hasattr(jax.lax, "pvary"):
            act0 = jax.lax.pvary(act0, (axis,))
        _, ys = jax.lax.scan(tick, act0, jnp.arange(ticks))
        # ys[t] = this stage's output at tick t; the final stage emitted
        # microbatch j at tick j + S - 1
        idx = jnp.arange(m) + (n_stages - 1)
        out = ys[idx]  # only meaningful on the last stage
        out = out.reshape((m * out.shape[1],) + out.shape[2:])
        # broadcast the last stage's result to every device (replicated
        # output): zero the other stages' buffers and psum over the axis
        out = jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis)

    def run(stacked_params, x):
        # dp×pp: when the mesh carries a 'data' axis, the batch shards over
        # it and each data-slice runs its own pipeline; gradients all-reduce
        # over 'data' automatically (GSPMD) in the surrounding jit
        dspec = ("data" if "data" in mesh.axis_names and axis != "data"
                 else None)
        f = shard_map(
            per_device, mesh=mesh,
            in_specs=(pipeline_spec(stacked_params, axis), P(dspec)),
            out_specs=P(dspec))
        return f(stacked_params, x)

    return run


class PipelineParallelTrainer:
    """Pipeline-parallel trainer: stages of shape-preserving blocks + an
    output head, trained with jax.grad THROUGH the pipeline schedule (the
    scan/ppermute program is differentiable end to end).

    Product surface (round-5 verdict item 2): takes the standard
    ``nn/updater.py`` updaters (incl. schedules), the ``nn/listeners.py``
    listener family, and a ``parallel/checkpoint.py`` TrainingCheckpointer —
    the same training amenities the single-chip ``fit()`` path has. Build
    either from raw stage/head callables, or from layer CONFIGS via
    ``from_confs`` (a config-built transformer trains dp×pp through
    ``fit()`` — tests/test_pipeline_moe.py asserts collectives + loss
    convergence on the CPU mesh).
    """

    def __init__(self, stage_fn: Callable, head_fn: Callable, mesh: Mesh,
                 *, num_microbatches: int, axis: str = "pipe",
                 updater=None, listeners=(), checkpointer=None,
                 checkpoint_every: int = 50):
        from deeplearning4j_tpu.nn.updater import Sgd, get_updater

        self.stage_fn = stage_fn
        self.head_fn = head_fn
        self.mesh = mesh
        self.axis = axis
        self.num_microbatches = num_microbatches
        self.updater = (get_updater(updater) if updater is not None
                        else Sgd(learning_rate=0.1))
        self.listeners = list(listeners)
        self.checkpointer = checkpointer
        self.checkpoint_every = checkpoint_every
        self.step_count = 0
        self.stacked_params = None
        self.head_params = None
        self.opt_state = None
        self._fwd = pipeline_forward(stage_fn, mesh,
                                     num_microbatches=num_microbatches,
                                     axis=axis)
        self._jit_step = None

    # ------------------------------------------------------------- builders
    @classmethod
    def from_confs(cls, block_confs, head_fn: Callable, input_feats,
                   mesh: Mesh, *, num_microbatches: int, n_stages=None,
                   seed: int = 0, head_params=None, axis: str = "pipe",
                   **kw) -> "PipelineParallelTrainer":
        """Config-built pipeline: one STAGE = the given list of shape-
        preserving LayerConfs (e.g. a transformer block expressed as
        DenseLayer/SelfAttentionLayer confs); every pipe device runs an
        identically-configured stage with its own weights.

        head_fn(head_params, feats, labels) -> scalar loss stays a callable
        (the head runs outside the pipeline, replicated)."""
        from deeplearning4j_tpu.nn import conf as C
        from deeplearning4j_tpu.nn.layers import build_layer

        n_stages = n_stages or mesh.shape[axis]
        # input_feats: an int (feed-forward width) or a full InputType
        # (e.g. InputType.recurrent(d, T) for transformer-block stages)
        in_type = (input_feats if isinstance(input_feats, C.InputType)
                   else C.InputType.feed_forward(input_feats))
        b = C.builder().seed(seed).list()
        for lc in block_confs:
            b.layer(lc)
        built = b.set_input_type(in_type).build()
        itype = built.input_type
        impls = []
        for lc in built.layers:  # n_in already inferred by build()
            impl = build_layer(built, lc, itype)
            impls.append(impl)
            itype = impl.otype
        if itype.flat_size() != in_type.flat_size():
            raise ValueError(
                f"pipeline stages must be shape-preserving: block maps "
                f"{in_type.flat_size()} -> {itype.flat_size()} features")

        def stage_fn(stage_params, x):
            for impl, p in zip(impls, stage_params):
                x, _, _ = impl.apply(p, x, impl.init_state(), train=True,
                                     rng=None, mask=None)
            return x

        key = jax.random.key(seed)
        per_stage = []
        for s in range(n_stages):
            keys = jax.random.split(jax.random.fold_in(key, s), len(impls))
            per_stage.append([impl.init(k) for impl, k in zip(impls, keys)])
        trainer = cls(stage_fn, head_fn, mesh,
                      num_microbatches=num_microbatches, axis=axis, **kw)
        trainer.init_params(stack_stage_params(per_stage), head_params or {})
        return trainer

    def init_params(self, stacked_params, head_params) -> None:
        self.stacked_params = stacked_params
        self.head_params = head_params
        self.opt_state = jax.tree.map(
            lambda p: self.updater.init_state(p),
            (stacked_params, head_params),
            is_leaf=lambda x: isinstance(x, jax.Array) or hasattr(x, "shape"))

    # -------------------------------------------------------------- training
    def loss_fn(self, stacked_params, head_params, x, y):
        feats = self._fwd(stacked_params, x)
        return self.head_fn(head_params, feats, y)

    def make_train_step(self, lr=None):
        """One jitted step using the configured updater (the historical
        ``lr`` argument overrides the updater with plain SGD for
        compatibility)."""
        from deeplearning4j_tpu.nn.updater import Sgd

        updater = Sgd(learning_rate=lr) if lr is not None else self.updater
        grad_fn = jax.value_and_grad(self.loss_fn, argnums=(0, 1))

        @jax.jit
        def step(stacked_params, head_params, opt_state, step_idx, x, y):
            loss, (gs, gh) = grad_fn(stacked_params, head_params, x, y)
            lr_t = updater.lr(step_idx)
            params = (stacked_params, head_params)
            grads = (gs, gh)
            flat_p, treedef = jax.tree.flatten(params)
            flat_g = treedef.flatten_up_to(grads)
            flat_s = treedef.flatten_up_to(opt_state)
            new_p, new_s = [], []
            for pw, gw, sw in zip(flat_p, flat_g, flat_s):
                u, ns = updater.apply(gw, sw, lr_t, step_idx)
                new_p.append(pw - u)
                new_s.append(ns)
            (sp, hp) = treedef.unflatten(new_p)
            return sp, hp, treedef.unflatten(new_s), loss

        return step

    def fit_step(self, x, y) -> float:
        """One training step through the standard path: updater math,
        listeners, periodic checkpointing."""
        if self._jit_step is None:
            self._jit_step = self.make_train_step()
        from deeplearning4j_tpu import observe
        observe.note_jit_signature(
            self._jit_step, graph="parallel", key="pipeline_train_step",
            signature=observe.signature_of(x=x, y=y))
        (self.stacked_params, self.head_params, self.opt_state,
         loss) = self._jit_step(self.stacked_params, self.head_params,
                                self.opt_state,
                                jnp.asarray(self.step_count, jnp.int32), x, y)
        score = float(loss)
        self.score = score
        self.step_count += 1
        for lst in self.listeners:
            lst.iteration_done(self, self.step_count, 0, score)
        if (self.checkpointer is not None
                and self.step_count % self.checkpoint_every == 0):
            self.checkpointer.save(self.step_count, self)
        return score

    def fit(self, x, y, steps: int = 1):
        return [self.fit_step(x, y) for _ in range(steps)]

    # ---- TrainingCheckpointer/listener protocol (net-like view) ----------
    @property
    def params(self):
        return (self.stacked_params, self.head_params)

    @params.setter
    def params(self, value):
        self.stacked_params, self.head_params = value

    @property
    def net_state(self):
        return {}

    @net_state.setter
    def net_state(self, value):
        pass

    @property
    def iteration_count(self):
        return self.step_count

    @iteration_count.setter
    def iteration_count(self, value):
        self.step_count = int(value)

    epoch_count = 0
