"""Distributed training — mesh DP/TP, checkpointing, multi-host bootstrap.

Reference parity: deeplearning4j-scaleout (ParallelWrapper, Spark masters),
nd4j-parameter-server (SURVEY §3.5) — realized as XLA collectives over a
jax.sharding.Mesh instead of Aeron/Spark transports."""

from deeplearning4j_tpu.parallel.mesh import (
    make_mesh,
    shard_params,
    ParallelWrapper,
    ParallelInference,
    DEFAULT_TP_RULES,
)
from deeplearning4j_tpu.parallel.checkpoint import (
    TrainingCheckpointer,
    CheckpointTrainingListener,
    CheckpointWriteError,
)
from deeplearning4j_tpu.parallel.supervisor import TrainingSupervisor
from deeplearning4j_tpu.parallel.launch import (
    initialize_distributed,
    host_shard,
    ShardedDataSetIterator,
)
from deeplearning4j_tpu.parallel.ring_attention import (
    ring_attention,
    RingSelfAttention,
)
from deeplearning4j_tpu.parallel.ulysses import ulysses_attention
