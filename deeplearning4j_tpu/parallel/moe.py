"""Expert parallelism — a Mixture-of-Experts layer sharded over an
``expert`` mesh axis with all_to_all token dispatch.

Reference parity: none — the reference has no MoE; this is the EXCEEDS-
reference expert-parallel axis the driver's multichip contract names
(tp/pp/dp/sp/ep). Design follows the public Switch-Transformer/GShard
recipe: top-1 token routing, per-expert capacity with drop-and-residual
overflow, all_to_all over ICI to move tokens to their expert's device and
back, plus the standard load-balancing auxiliary loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int,
                    dtype=jnp.float32):
    """Router + per-expert MLP params, experts stacked on the leading axis
    (shard it over the 'expert' mesh axis)."""
    kr, k1, k2 = jax.random.split(key, 3)
    s1 = (2.0 / d_model) ** 0.5
    return {
        "router": (jax.random.normal(kr, (d_model, n_experts), dtype)
                   * (1.0 / d_model) ** 0.5),
        "W1": jax.random.normal(k1, (n_experts, d_model, d_hidden),
                                dtype) * s1,
        "W2": jax.random.normal(k2, (n_experts, d_hidden, d_model), dtype)
        * (2.0 / d_hidden) ** 0.5,
    }


def moe_spec(axis: str = "expert"):
    """PartitionSpecs for init_moe_params output: experts sharded, router
    replicated."""
    return {"router": P(), "W1": P(axis, None, None),
            "W2": P(axis, None, None)}


def moe_forward(mesh: Mesh, *, n_experts: int, capacity_factor: float = 1.25,
                axis: str = "expert"):
    """Build a jittable f(params, x) -> (y, aux_loss) running top-1 MoE
    with expert-parallel dispatch.

    x: (tokens, d_model), tokens divisible by the expert-axis size. Each
    device routes its local tokens, all_to_all ships them to their
    expert's device (capacity C per expert per source device), the local
    expert MLP runs ONE batched matmul pair, and a second all_to_all
    returns results. Dropped (over-capacity) tokens pass through
    residually, Switch-Transformer style.
    """
    ep = mesh.shape[axis]
    assert n_experts % ep == 0, (n_experts, ep)
    experts_per_device = n_experts // ep

    def per_device(params, x_local):
        t_local, d = x_local.shape
        cap = int(np.ceil(capacity_factor * t_local / n_experts))

        logits = x_local @ params["router"]              # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)          # (T,)
        gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

        # load-balancing aux loss (Switch eq. 4): E * sum(frac_i * prob_i)
        frac = jnp.mean(jax.nn.one_hot(expert_idx, n_experts), axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = n_experts * jnp.sum(frac * mean_prob)

        # position of each token within its expert's capacity buffer
        onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1)
        pos = jnp.take_along_axis(pos_in_expert, expert_idx[:, None],
                                  axis=1)[:, 0]
        keep = pos < cap

        # scatter tokens into (E, cap, d) send buffer
        buf = jnp.zeros((n_experts, cap, d), x_local.dtype)
        buf = buf.at[jnp.where(keep, expert_idx, 0),
                     jnp.where(keep, pos, 0)].add(
            jnp.where(keep[:, None], x_local, 0.0))

        # ship: regroup (E, cap, d) -> (ep, e_per_dev, cap, d), all_to_all
        send = buf.reshape(ep, experts_per_device, cap, d)
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        # recv: (ep_src, e_per_dev, cap, d) — tokens from every source
        # device for THIS device's experts
        tokens = recv.transpose(1, 0, 2, 3).reshape(
            experts_per_device, ep * cap, d)
        w1 = params["W1"]                                # (e_per_dev, d, h)
        w2 = params["W2"]
        h = jax.nn.relu(jnp.einsum("etd,edh->eth", tokens, w1))
        out = jnp.einsum("eth,ehd->etd", h, w2)
        out = out.reshape(experts_per_device, ep, cap, d).transpose(
            1, 0, 2, 3)
        back = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        back = back.reshape(n_experts, cap, d)

        # gather each token's result; dropped tokens pass through
        got = back[jnp.where(keep, expert_idx, 0),
                   jnp.where(keep, pos, 0)]
        y = jnp.where(keep[:, None], gate[:, None] * got, x_local)
        return y, aux.reshape(1)

    def run(params, x):
        f = shard_map(
            per_device, mesh=mesh,
            in_specs=(moe_spec(axis), P(axis, None)),
            out_specs=(P(axis, None), P(axis)),
            )
        y, aux = f(params, x)
        return y, jnp.mean(aux)

    return run
