"""Sharded checkpoint/resume — the large-scale persistence path.

Reference parity (SURVEY §6.4):
  * CheckpointListener periodic saves + ModelSerializer artifacts are the
    reference's recovery story; cluster state is NOT checkpointed — resume is
    params-only. Elasticity = checkpoint-restart (SURVEY §6.3).

TPU-native realization: orbax (in env) for async, per-host-sharded
checkpoints of the full training state (params + updater state + net state +
step + RNG key + data cursor). Falls back to a .npz scheme when orbax is
unavailable. The user-facing ModelSerializer zip (nn/serde.py) remains the
parity surface for single-host models; this module is the pod-scale path.

Durability (docs/ROBUSTNESS.md): the .npz path writes ATOMICALLY — temp
file + fsync + rename — so a crash mid-save can never leave a torn file
under the final name, and the ``latest.json`` marker records a sha256
content checksum per checkpoint. ``restore`` verifies the checksum before
loading and FALLS BACK to the newest intact checkpoint on corruption
(counted in ``dl4j_tpu_checkpoint_corrupt_total`` /
``dl4j_tpu_checkpoint_fallback_total``) instead of raising mid-``fit`` —
a relaunched elastic job loses at most one save interval, never the run.
The ``checkpoint_torn_write`` fault point (deeplearning4j_tpu/faults/)
corrupts the just-written file to prove that path under test.

Async snapshot checkpointing (docs/ROBUSTNESS.md § Preemption-proof
training): ``save_async`` splits a save into the part that must block the
training thread — one ``jax.device_get`` snapshot at a step boundary —
and the part that must not: the atomic tmp+fsync+replace+sha256 dance,
which a bounded background writer thread performs off the hot path. A
full queue either drops the OLDEST pending snapshot (``drop_oldest``,
default — newest state wins under backpressure) or blocks the trainer
(``block`` — every snapshot durable, at step-time cost). Retention is
in-flight-aware (queued snapshots never count toward ``keep_last``, and
the newest INTACT checkpoint is never evicted), writer failures are
surfaced loudly on the next save, and ``wait_until_finished()`` drains
the queue before a restore or process exit.
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, observe

logger = logging.getLogger(__name__)

#: overflow policies for the bounded async writer queue
OVERFLOW_POLICIES = ("drop_oldest", "block")


class CheckpointWriteError(RuntimeError):
    """Raised on the NEXT save when a background checkpoint write failed —
    an async failure must not stay silent until restore time."""

    def __init__(self, failures: List[Tuple[int, BaseException]]):
        steps = [s for s, _ in failures]
        super().__init__(
            f"async checkpoint write failed for step(s) {steps}: "
            f"{failures[-1][1]!r}")
        self.failures = failures


def _try_orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


class _AsyncWriter:
    """Bounded background writer: the training thread enqueues host
    snapshots; this thread does the durable write. One writer per
    checkpointer — writes stay ordered, the marker stays consistent."""

    def __init__(self, ckpt: "TrainingCheckpointer", max_queue: int,
                 overflow: str):
        if overflow not in OVERFLOW_POLICIES:
            raise ValueError(
                f"overflow must be one of {OVERFLOW_POLICIES}, "
                f"got {overflow!r}")
        self._ckpt = ckpt
        self._max_queue = max(1, int(max_queue))
        self._overflow = overflow
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._in_flight: Optional[int] = None  # step currently being written
        self._failures: List[Tuple[int, BaseException]] = []
        self._stop = False
        self._warned_drop = False
        self._thread: Optional[threading.Thread] = None
        m = observe.metrics()
        self._depth_g = m.gauge("dl4j_tpu_ckpt_queue_depth")
        self._saves_c = m.counter("dl4j_tpu_ckpt_async_saves_total")
        self._dropped_c = m.counter("dl4j_tpu_ckpt_dropped_total")
        self._blocked_c = m.counter("dl4j_tpu_ckpt_blocked_total")
        self._write_h = m.histogram("dl4j_tpu_ckpt_write_seconds")

    # ------------------------------------------------------- trainer side
    def _ensure_thread(self) -> None:
        with self._cv:
            # _stop is read under _cv by the writer's wait loops; writing
            # it bare here could race a concurrent stop() and leave a
            # freshly started thread believing it should exit (or a
            # stopping one believing it should not)
            if self._thread is None or not self._thread.is_alive():
                self._stop = False  # a close()d writer restarts on next use
                self._thread = threading.Thread(
                    target=self._run, name="ckpt-writer", daemon=True)
                self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Drain the queue, then retire the writer thread. Without this a
        short-lived checkpointer (benchmarks, tests, per-run directory
        rotation) leaks an idle daemon thread — and its reference to the
        whole checkpointer — for the process lifetime. Idempotent; a
        later ``submit`` transparently restarts the writer."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
        self._thread = None

    def take_failures(self) -> List[Tuple[int, BaseException]]:
        with self._cv:
            out, self._failures = self._failures, []
        return out

    def submit(self, step: int, host_state: Dict[str, Any]) -> None:
        """Enqueue a host snapshot (already device_get — the writer never
        touches device buffers, so donation in the next train step is
        safe). Applies the overflow policy; raises pending failures."""
        failures = self.take_failures()
        if failures:
            raise CheckpointWriteError(failures)
        self._ensure_thread()
        with self._cv:
            if len(self._q) >= self._max_queue:
                if self._overflow == "drop_oldest":
                    dropped_step, _ = self._q.popleft()
                    self._dropped_c.inc()
                    # dropping is this policy's NORMAL backpressure mode —
                    # warn once, then stay quiet (the counter keeps score)
                    log = (logger.warning if not self._warned_drop
                           else logger.debug)
                    self._warned_drop = True
                    log("async checkpoint queue full — dropped pending "
                        "snapshot for step %d (drop_oldest; counted in "
                        "dl4j_tpu_ckpt_dropped_total)", dropped_step)
                else:  # block
                    self._blocked_c.inc()
                    while len(self._q) >= self._max_queue and not self._stop:
                        self._cv.wait(timeout=0.1)
            self._q.append((step, host_state))
            self._depth_g.set(len(self._q))
            self._cv.notify_all()

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued snapshot has been written (or dropped)
        and nothing is in flight. Returns False on timeout."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._cv:
            while self._q or self._in_flight is not None:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        return False
                self._cv.wait(timeout=remaining if remaining is not None
                              else 0.5)
        return True

    def pending(self) -> int:
        with self._cv:
            return len(self._q) + (self._in_flight is not None)

    # -------------------------------------------------------- writer side
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop and not self._q:
                    return
                was_full = len(self._q) >= self._max_queue
                step, host_state = self._q.popleft()
                if self._overflow == "drop_oldest" and was_full:
                    # coalesce UNDER BACKPRESSURE only: when the queue is
                    # full the newest state wins and writing snapshots a
                    # queued newer one supersedes is wasted IO/CPU against
                    # the trainer. A non-full queue writes in order — the
                    # denser durable history keeps more fallback points.
                    while self._q:
                        self._dropped_c.inc()
                        step, host_state = self._q.popleft()
                self._in_flight = step
                depth = len(self._q)
                self._depth_g.set(depth)
                self._cv.notify_all()
            t0 = time.perf_counter()
            try:
                # chaos (docs/ROBUSTNESS.md): worker_death fires INSIDE the
                # durable write (see _write_npz) — the checkpoint is lost,
                # training must not be; the failure surfaces on the next save
                self._ckpt._write_and_record(step, host_state)
                dt = time.perf_counter() - t0
                self._write_h.observe(dt)
                self._saves_c.inc()
                observe.log_event("ckpt_async", step=step,
                                  write_s=round(dt, 6),
                                  queue_depth=depth)
            except BaseException as e:  # surfaced on the next save
                logger.warning(
                    "async checkpoint write for step %d failed: %r", step, e)
                with self._cv:
                    self._failures.append((step, e))
            finally:
                with self._cv:
                    self._in_flight = None
                    self._cv.notify_all()


class TrainingCheckpointer:
    """Checkpoint the FULL training state for exact resume.

    save(step, net) / save_async(step, net) / restore(net) -> step.
    Directory layout: <dir>/step_<N>/ (orbax) or <dir>/step_<N>.npz
    (fallback), plus latest.json marker (carrying a sha256 per .npz
    checkpoint). keep_last retention mirrors CheckpointListener but never
    evicts the newest INTACT checkpoint and never counts queued async
    writes. Saves are atomic and restores verify + fall back — see the
    module docstring.

    State protocol: a net either exposes ``training_state()`` /
    ``apply_training_state(state)`` (SameDiff), or the default attribute
    set ``params / opt_state / net_state / iteration_count / epoch_count``
    plus the optional ``_key`` RNG stream and ``batch_in_epoch`` data
    cursor (MultiLayerNetwork / ComputationGraph). Either way the payload
    covers everything exact resume needs: a killed-and-resumed fit is
    bit-for-bit the uninterrupted one.
    """

    def __init__(self, directory: str, keep_last: Optional[int] = 3,
                 use_orbax: Optional[bool] = None,
                 max_queue: int = 2, overflow: str = "drop_oldest"):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.keep_last = keep_last
        ocp = _try_orbax() if use_orbax in (None, True) else None
        self._ocp = ocp
        self._saved: list = []
        # retention-only verify memo keyed on (size, mtime_ns): steady-
        # state pruning must not re-read+hash the newest checkpoint on
        # every save; any on-disk change (the torn-write fault truncates)
        # invalidates the entry. restore() always verifies uncached.
        self._verify_cache: Dict[str, Tuple[Tuple[int, int], bool]] = {}
        # one lock serializes marker/_saved/retention across the training
        # thread (sync saves, restore) and the async writer thread
        self._io_lock = threading.RLock()
        self._writer = _AsyncWriter(self, max_queue=max_queue,
                                    overflow=overflow)
        self._load_marker()
        # a writer killed mid-write (worker_death, SIGKILL) leaves its
        # step_*.npz.tmp behind — sweep them on restart, before any new
        # write could be racing for the same names
        self._cleanup_orphan_tmps()

    # ------------------------------------------------------------------ save
    def _state_of(self, net) -> Dict[str, Any]:
        if hasattr(net, "training_state"):
            return dict(net.training_state())
        state = {
            "params": net.params,
            "opt_state": net.opt_state,
            "net_state": net.net_state,
            "iteration": np.asarray(net.iteration_count),
            "epoch": np.asarray(net.epoch_count),
            # mid-epoch position: completed batches in the current epoch,
            # so resume replays exactly the unseen remainder
            "data_cursor": np.asarray(getattr(net, "batch_in_epoch", 0)),
        }
        key = getattr(net, "_key", None)
        if key is not None:
            # the training RNG stream is part of exact resume: without it a
            # relaunched job replays dropout masks from step 0
            state["rng_key"] = np.asarray(jax.random.key_data(key))
        return state

    @staticmethod
    def _sha256_of(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def _write_npz(self, step: int, state) -> Tuple[str, str]:
        """The durable .npz write: atomic tmp+fsync+replace, sha256 taken
        pre-publish. Runs on the caller's thread (sync save) or the writer
        thread (async)."""
        path = os.path.join(self.dir, f"step_{step}.npz")
        flat = {}
        leaves = jax.tree_util.tree_leaves_with_path(state)
        for kp, leaf in leaves:
            key = jax.tree_util.keystr(kp)
            flat[key] = np.asarray(leaf)
        # atomic: all bytes land (and reach disk — fsync) under a temp
        # name; the rename publishes a complete file or nothing. The
        # checksum is taken pre-publish so the marker always describes
        # the bytes the save INTENDED — later corruption (torn device,
        # the injected fault below) is caught by restore's verify.
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
            f.flush()
            os.fsync(f.fileno())
        checksum = self._sha256_of(tmp)
        # chaos (docs/ROBUSTNESS.md): worker_death strikes mid-write —
        # after the bytes land under the tmp name, before the publishing
        # rename. The checkpoint is lost AND its .tmp is orphaned; the
        # cleanup hooks (__init__, wait_until_finished) sweep it up.
        faults.maybe_fail("worker_death")
        os.replace(tmp, path)
        if faults.should_fire("checkpoint_torn_write"):
            # chaos (docs/ROBUSTNESS.md): simulate on-disk corruption
            # AFTER the atomic publish — exactly the case the marker
            # checksum + restore fallback exist for
            with open(path, "r+b") as f:
                f.truncate(max(1, os.path.getsize(path) // 2))
        return path, checksum

    def _write_and_record(self, step: int, state) -> str:
        """Durable write + marker/retention bookkeeping (both threads)."""
        if self._ocp is not None:
            path = os.path.join(self.dir, f"step_{step}")
            ckptr = self._ocp.StandardCheckpointer()
            ckptr.save(path, jax.device_get(state), force=True)
            ckptr.wait_until_finished()
            checksum = None
        else:
            path, checksum = self._write_npz(step, state)
        with self._io_lock:
            self._record_saved(step, path, checksum)
            self._retain()
            # ONE marker write per save, after retention settles — the
            # pruning pass must not cost a second fsync
            self._write_marker()
        observe.metrics().counter("dl4j_tpu_checkpoint_saves_total").inc()
        return path

    def save(self, step: int, net) -> str:
        """Synchronous save — blocks the caller through the durable write
        (the SIGTERM final-snapshot path, and the pre-async default)."""
        failures = self._writer.take_failures()
        if failures:
            raise CheckpointWriteError(failures)
        return self._write_and_record(step, self._state_of(net))

    def save_async(self, step: int, net) -> None:
        """Async save: snapshot the training state to host NOW (one
        ``jax.device_get`` at the step boundary — the only part the
        training thread pays for) and hand the bytes to the background
        writer. A failed background write raises here on the NEXT call."""
        host_state = jax.device_get(self._state_of(net))
        self._writer.submit(step, host_state)

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Drain the async queue (call before restore / process exit).
        Once drained, sweeps any orphaned ``step_*.npz.tmp`` a dead
        writer left behind — the queue is empty, so nothing is mid-write
        and every surviving .tmp is garbage."""
        ok = self._writer.wait_until_finished(timeout=timeout)
        if ok:
            self._cleanup_orphan_tmps()
        return ok

    def _cleanup_orphan_tmps(self) -> None:
        """Remove orphaned durable-write temporaries. Only call when no
        write is in flight (fresh __init__, drained queue)."""
        with self._io_lock:
            for tmp in glob.glob(os.path.join(self.dir, "step_*.npz.tmp")):
                try:
                    os.remove(tmp)
                except OSError:  # pragma: no cover - best-effort sweep
                    pass

    def drain_failures(self) -> List[Tuple[int, BaseException]]:
        """Take (and clear) any recorded background-write failures WITHOUT
        raising — the fit-end/preemption paths use this to decide on a
        compensating synchronous save instead of aborting on the stale
        failure that `save()` would re-raise."""
        return self._writer.take_failures()

    def close(self, timeout: float = 30.0) -> None:
        """Drain pending async writes and retire the writer thread (call
        when this checkpointer is done for good — benchmarks, tests,
        directory rotation). A later ``save_async`` restarts it."""
        self._writer.wait_until_finished(timeout=timeout)
        self._writer.stop()

    def pending_async(self) -> int:
        """Queued + in-flight async writes (test/diagnostic hook)."""
        return self._writer.pending()

    def _record_saved(self, step: int, path: str,
                      checksum: Optional[str]) -> None:
        """Insert sorted by step — a sync save (SIGTERM snapshot) can land
        while older async writes are still queued; restore's newest-first
        walk relies on the order. Call under ``_io_lock``."""
        entry = (step, path, checksum)
        self._saved = [e for e in self._saved if e[0] != step]
        idx = len(self._saved)
        while idx > 0 and self._saved[idx - 1][0] > step:
            idx -= 1
        self._saved.insert(idx, entry)

    def _write_marker(self) -> None:
        """Atomic marker update — a crash between checkpoint publish and
        marker write loses the newest entry, never the marker itself."""
        marker = os.path.join(self.dir, "latest.json")
        tmp = marker + ".tmp"
        newest = self._saved[-1] if self._saved else (None, None, None)
        with open(tmp, "w") as f:
            json.dump({"step": newest[0], "path": newest[1],
                       "saved": [[s, p, c] for s, p, c in self._saved]}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)

    def _retain(self):
        """keep_last pruning, newest-INTACT-aware: eviction walks oldest
        first but never deletes the newest checkpoint whose checksum still
        verifies — when every newer save is torn (or still queued in the
        async writer, where it does not count at all), the one restorable
        checkpoint survives. Call under ``_io_lock``."""
        if self.keep_last is None or len(self._saved) <= self.keep_last:
            return
        newest_intact = next(
            ((s, p, c) for s, p, c in reversed(self._saved)
             if self._verify_for_retention(p, c)), None)
        idx = 0
        while len(self._saved) > self.keep_last and idx < len(self._saved):
            entry = self._saved[idx]
            if entry == newest_intact:
                idx += 1  # never evict the only restorable checkpoint
                continue
            self._saved.pop(idx)
            _, old, _ = entry
            self._verify_cache.pop(old, None)  # keep the memo bounded
            if os.path.isdir(old):
                import shutil

                shutil.rmtree(old, ignore_errors=True)
            elif os.path.exists(old):
                os.remove(old)

    def _load_marker(self):
        marker = os.path.join(self.dir, "latest.json")
        if os.path.exists(marker):
            with open(marker) as f:
                d = json.load(f)
            self._saved = [
                # pre-robustness markers carry [step, path] pairs: keep
                # loading them (checksum None -> restore skips the verify)
                (e[0], e[1], e[2] if len(e) > 2 else None)
                for e in d.get("saved", []) if os.path.exists(e[1])]
            self._saved.sort(key=lambda e: e[0])

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        with self._io_lock:
            return self._saved[-1][0] if self._saved else None

    def _verify(self, path: str, checksum: Optional[str]) -> bool:
        """Content integrity: sha256 vs the marker (skip when the entry
        predates checksums or is an orbax directory)."""
        if checksum is None or os.path.isdir(path):
            return True
        try:
            return self._sha256_of(path) == checksum
        except OSError:
            return False

    def _verify_for_retention(self, path: str,
                              checksum: Optional[str]) -> bool:
        """Memoized verify for the pruning pass: a full read+hash of the
        newest checkpoint on EVERY save would double steady-state
        checkpoint IO. Cache keyed on (size, mtime_ns) — the corruption
        this layer models (post-publish truncation) always changes the
        stat signature."""
        try:
            st = os.stat(path)
        except OSError:
            return False
        key = (st.st_size, st.st_mtime_ns)
        hit = self._verify_cache.get(path)
        if hit is not None and hit[0] == key:
            return hit[1]
        ok = self._verify(path, checksum)
        self._verify_cache[path] = (key, ok)
        return ok

    def restore(self, net, step: Optional[int] = None) -> Optional[int]:
        """Restore into the net (its init() must already have built the
        matching pytree structure). Returns the restored step or None.

        With ``step=None`` candidates are tried NEWEST-FIRST: a checkpoint
        whose checksum mismatches (torn write, disk corruption) or whose
        load raises is skipped with a warning and the next-newest intact
        one is used — corruption costs one save interval, not the run.
        An explicitly requested ``step`` that is corrupt raises (the
        caller asked for those exact bytes)."""
        with self._io_lock:
            saved = list(self._saved)
        if not saved:
            return None
        if step is None:
            candidates = list(reversed(saved))
        else:
            wanted = next(((s, p, c) for s, p, c in saved if s == step),
                          None)
            if wanted is None:
                # a bare next() would raise StopIteration here — silently
                # swallowed inside generator machinery; name the problem
                raise ValueError(
                    f"no checkpoint recorded for step {step} under "
                    f"{self.dir} (retention may have pruned it); known "
                    f"steps: {[s for s, _, _ in saved]}")
            candidates = [wanted]
        newest = candidates[0][0]
        for cand_step, path, checksum in candidates:
            if not self._verify(path, checksum):
                observe.metrics().counter(
                    "dl4j_tpu_checkpoint_corrupt_total").inc()
                if step is not None:
                    raise IOError(
                        f"checkpoint step {cand_step} at {path} failed its "
                        f"integrity check (torn write?)")
                logger.warning(
                    "checkpoint step %d at %s failed its integrity check — "
                    "falling back to the next-newest intact checkpoint",
                    cand_step, path)
                continue
            try:
                restored = self._load_state(net, path)
            except Exception as e:
                observe.metrics().counter(
                    "dl4j_tpu_checkpoint_corrupt_total").inc()
                if step is not None:
                    raise
                logger.warning(
                    "checkpoint step %d at %s failed to load (%r) — "
                    "falling back", cand_step, path, e)
                continue
            if cand_step != newest:
                observe.metrics().counter(
                    "dl4j_tpu_checkpoint_fallback_total").inc()
                observe.log_event("checkpoint_fallback",
                                  wanted=newest, used=cand_step)
            self._apply_state(net, restored)
            return cand_step
        logger.warning(
            "no intact checkpoint found under %s — restore skipped "
            "(training resumes from the net's current state)", self.dir)
        return None

    def _load_state(self, net, path: str) -> Dict[str, Any]:
        target = self._state_of(net)
        if self._ocp is not None and os.path.isdir(path):
            ckptr = self._ocp.StandardCheckpointer()
            return ckptr.restore(path, target=jax.device_get(target))
        data = np.load(path)
        leaves_p = jax.tree_util.tree_leaves_with_path(target)
        restored_leaves = []
        for kp, leaf in leaves_p:
            key = jax.tree_util.keystr(kp)
            if key not in data and (key.startswith("['rng_key']")
                                    or key.startswith("['data_cursor']")):
                # checkpoints predating the RNG stream / data cursor: keep
                # the net's current value rather than failing the restore
                restored_leaves.append(np.asarray(leaf))
                continue
            restored_leaves.append(data[key])
        treedef = jax.tree_util.tree_structure(target)
        return jax.tree_util.tree_unflatten(treedef, restored_leaves)

    def _apply_state(self, net, restored: Dict[str, Any]) -> None:
        if hasattr(net, "apply_training_state"):
            net.apply_training_state(restored)
            return
        net.params = jax.tree.map(jnp.asarray, restored["params"])
        net.opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
        net.net_state = jax.tree.map(jnp.asarray, restored["net_state"])
        net.iteration_count = int(restored["iteration"])
        net.epoch_count = int(restored["epoch"])
        if "data_cursor" in restored:
            net.batch_in_epoch = int(restored["data_cursor"])
        if "rng_key" in restored and getattr(net, "_key", None) is not None:
            net._key = jax.random.wrap_key_data(
                jnp.asarray(restored["rng_key"]),
                impl=jax.random.key_impl(net._key))


class CheckpointTrainingListener:
    """Periodic TrainingCheckpointer saves as a listener — the pod-scale
    CheckpointListener.

    ``asynchronous=True`` routes periodic saves through the background
    writer (one device_get on the training thread, durable write off it).
    The fit-end hook always saves SYNCHRONOUSLY when the final step missed
    the ``every_n_iterations`` boundary — a run never loses its tail — and
    ``on_preemption`` takes the final SIGTERM snapshot. A checkpointer
    raise inside ``iteration_done`` warns ONCE and lets training continue:
    a broken disk costs durability, never the run."""

    #: fit loops with sub-batch listener granularity (ComputationGraph
    #: tbptt segments) skip this listener mid-batch and give it one
    #: batch-boundary call instead — a mid-batch snapshot (live RNN carry,
    #: stale cursor) could never resume exactly
    defers_mid_tbptt = True

    def __init__(self, checkpointer: TrainingCheckpointer,
                 every_n_iterations: int = 100, asynchronous: bool = False):
        self.ckpt = checkpointer
        self.every = max(1, every_n_iterations)
        self.asynchronous = asynchronous
        self.last_saved_iteration: Optional[int] = None
        self._warned = False

    def _save(self, model, iteration: int, sync: bool = False) -> None:
        try:
            if self.asynchronous and not sync:
                self.ckpt.save_async(iteration, model)
            else:
                self.ckpt.save(iteration, model)
            self.last_saved_iteration = iteration
        except Exception as e:
            if not self._warned:
                self._warned = True
                logger.warning(
                    "checkpoint save at iteration %d failed (%r) — training "
                    "continues WITHOUT durability; further failures "
                    "suppressed", iteration, e)

    def iteration_done(self, model, iteration, epoch, score):
        if getattr(model, "_tbptt_mid_batch", False):
            return  # deferred to the batch boundary (defers_mid_tbptt)
        if iteration % self.every == 0:
            self._save(model, iteration)

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def fit_done(self, model):
        """Final checkpoint at fit end: a run whose last step misses the
        periodic boundary must not lose its tail. ``last_saved_iteration``
        advances on async SUBMISSION, so confirm durability: drain the
        writer, and if the tail write actually FAILED in the background,
        compensate with a synchronous save."""
        it = int(getattr(model, "iteration_count",
                         getattr(model, "_step", 0)))
        if not it:
            return
        failed = []
        if self.asynchronous:
            self.ckpt.wait_until_finished(timeout=60.0)
            failed = self.ckpt.drain_failures()
            if failed:
                logger.warning(
                    "async checkpoint write(s) for step(s) %s failed in "
                    "the background — taking a compensating synchronous "
                    "final save", [s for s, _ in failed])
        if failed or it != self.last_saved_iteration:
            self._save(model, it, sync=True)

    def on_preemption(self, model):
        """SIGTERM grace period: one final SYNCHRONOUS snapshot — the
        process may die right after, so the write must be durable now
        (a stale background failure must not abort it either)."""
        it = int(getattr(model, "iteration_count",
                         getattr(model, "_step", 0)))
        self.ckpt.wait_until_finished(timeout=30.0)
        self.ckpt.drain_failures()
        self._save(model, it, sync=True)
