"""Sharded checkpoint/resume — the large-scale persistence path.

Reference parity (SURVEY §6.4):
  * CheckpointListener periodic saves + ModelSerializer artifacts are the
    reference's recovery story; cluster state is NOT checkpointed — resume is
    params-only. Elasticity = checkpoint-restart (SURVEY §6.3).

TPU-native realization: orbax (in env) for async, per-host-sharded
checkpoints of the full training state (params + updater state + net state +
step + RNG key). Falls back to a .npz scheme when orbax is unavailable. The
user-facing ModelSerializer zip (nn/serde.py) remains the parity surface for
single-host models; this module is the pod-scale path.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _try_orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


class TrainingCheckpointer:
    """Checkpoint the FULL training state for exact resume.

    save(step, net) / restore(net) -> step. Directory layout:
    <dir>/step_<N>/ (orbax) or <dir>/step_<N>.npz (fallback), plus
    latest.json marker. keep_last retention mirrors CheckpointListener.
    """

    def __init__(self, directory: str, keep_last: Optional[int] = 3,
                 use_orbax: Optional[bool] = None):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.keep_last = keep_last
        ocp = _try_orbax() if use_orbax in (None, True) else None
        self._ocp = ocp
        self._saved: list = []
        self._load_marker()

    # ------------------------------------------------------------------ save
    def _state_of(self, net) -> Dict[str, Any]:
        state = {
            "params": net.params,
            "opt_state": net.opt_state,
            "net_state": net.net_state,
            "iteration": np.asarray(net.iteration_count),
            "epoch": np.asarray(net.epoch_count),
        }
        key = getattr(net, "_key", None)
        if key is not None:
            # the training RNG stream is part of exact resume: without it a
            # relaunched job replays dropout masks from step 0
            state["rng_key"] = np.asarray(jax.random.key_data(key))
        return state

    def save(self, step: int, net) -> str:
        state = self._state_of(net)
        if self._ocp is not None:
            path = os.path.join(self.dir, f"step_{step}")
            ckptr = self._ocp.StandardCheckpointer()
            ckptr.save(path, jax.device_get(state), force=True)
            ckptr.wait_until_finished()
        else:
            path = os.path.join(self.dir, f"step_{step}.npz")
            flat = {}
            leaves = jax.tree_util.tree_leaves_with_path(state)
            for kp, leaf in leaves:
                key = jax.tree_util.keystr(kp)
                flat[key] = np.asarray(leaf)
            np.savez(path, **flat)
        self._saved.append((step, path))
        with open(os.path.join(self.dir, "latest.json"), "w") as f:
            json.dump({"step": step, "path": path,
                       "saved": [[s, p] for s, p in self._saved]}, f)
        self._retain()
        return path

    def _retain(self):
        if self.keep_last is None:
            return
        while len(self._saved) > self.keep_last:
            _, old = self._saved.pop(0)
            if os.path.isdir(old):
                import shutil

                shutil.rmtree(old, ignore_errors=True)
            elif os.path.exists(old):
                os.remove(old)

    def _load_marker(self):
        marker = os.path.join(self.dir, "latest.json")
        if os.path.exists(marker):
            with open(marker) as f:
                d = json.load(f)
            self._saved = [(s, p) for s, p in d.get("saved", []) if os.path.exists(p)]

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._saved[-1][0] if self._saved else None

    def restore(self, net, step: Optional[int] = None) -> Optional[int]:
        """Restore into the net (its init() must already have built the
        matching pytree structure). Returns the restored step or None."""
        if not self._saved:
            return None
        step, path = self._saved[-1] if step is None else next(
            (s, p) for s, p in self._saved if s == step)
        target = self._state_of(net)
        if self._ocp is not None and os.path.isdir(path):
            ckptr = self._ocp.StandardCheckpointer()
            restored = ckptr.restore(path, target=jax.device_get(target))
        else:
            data = np.load(path)
            leaves_p = jax.tree_util.tree_leaves_with_path(target)
            restored_leaves = []
            for kp, leaf in leaves_p:
                key = jax.tree_util.keystr(kp)
                if key not in data and key.startswith("['rng_key']"):
                    # pre-round-4 checkpoint without the RNG stream: keep
                    # the net's current key rather than failing the restore
                    restored_leaves.append(np.asarray(leaf))
                    continue
                restored_leaves.append(data[key])
            treedef = jax.tree_util.tree_structure(target)
            restored = jax.tree_util.tree_unflatten(treedef, restored_leaves)
        net.params = jax.tree.map(jnp.asarray, restored["params"])
        net.opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
        net.net_state = jax.tree.map(jnp.asarray, restored["net_state"])
        net.iteration_count = int(restored["iteration"])
        net.epoch_count = int(restored["epoch"])
        if "rng_key" in restored and getattr(net, "_key", None) is not None:
            net._key = jax.random.wrap_key_data(
                jnp.asarray(restored["rng_key"]),
                impl=jax.random.key_impl(net._key))
        return step


class CheckpointTrainingListener:
    """Periodic TrainingCheckpointer saves as a listener — the pod-scale
    CheckpointListener."""

    def __init__(self, checkpointer: TrainingCheckpointer, every_n_iterations: int = 100):
        self.ckpt = checkpointer
        self.every = max(1, every_n_iterations)

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.every == 0:
            self.ckpt.save(iteration, model)

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass
