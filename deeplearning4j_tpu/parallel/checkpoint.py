"""Sharded checkpoint/resume — the large-scale persistence path.

Reference parity (SURVEY §6.4):
  * CheckpointListener periodic saves + ModelSerializer artifacts are the
    reference's recovery story; cluster state is NOT checkpointed — resume is
    params-only. Elasticity = checkpoint-restart (SURVEY §6.3).

TPU-native realization: orbax (in env) for async, per-host-sharded
checkpoints of the full training state (params + updater state + net state +
step + RNG key). Falls back to a .npz scheme when orbax is unavailable. The
user-facing ModelSerializer zip (nn/serde.py) remains the parity surface for
single-host models; this module is the pod-scale path.

Durability (docs/ROBUSTNESS.md): the .npz path writes ATOMICALLY — temp
file + fsync + rename — so a crash mid-save can never leave a torn file
under the final name, and the ``latest.json`` marker records a sha256
content checksum per checkpoint. ``restore`` verifies the checksum before
loading and FALLS BACK to the newest intact checkpoint on corruption
(counted in ``dl4j_tpu_checkpoint_corrupt_total`` /
``dl4j_tpu_checkpoint_fallback_total``) instead of raising mid-``fit`` —
a relaunched elastic job loses at most one save interval, never the run.
The ``checkpoint_torn_write`` fault point (deeplearning4j_tpu/faults/)
corrupts the just-written file to prove that path under test.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, observe

logger = logging.getLogger(__name__)


def _try_orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


class TrainingCheckpointer:
    """Checkpoint the FULL training state for exact resume.

    save(step, net) / restore(net) -> step. Directory layout:
    <dir>/step_<N>/ (orbax) or <dir>/step_<N>.npz (fallback), plus
    latest.json marker (now carrying a sha256 per .npz checkpoint).
    keep_last retention mirrors CheckpointListener. Saves are atomic and
    restores verify + fall back — see the module docstring.
    """

    def __init__(self, directory: str, keep_last: Optional[int] = 3,
                 use_orbax: Optional[bool] = None):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.keep_last = keep_last
        ocp = _try_orbax() if use_orbax in (None, True) else None
        self._ocp = ocp
        self._saved: list = []
        self._load_marker()

    # ------------------------------------------------------------------ save
    def _state_of(self, net) -> Dict[str, Any]:
        state = {
            "params": net.params,
            "opt_state": net.opt_state,
            "net_state": net.net_state,
            "iteration": np.asarray(net.iteration_count),
            "epoch": np.asarray(net.epoch_count),
        }
        key = getattr(net, "_key", None)
        if key is not None:
            # the training RNG stream is part of exact resume: without it a
            # relaunched job replays dropout masks from step 0
            state["rng_key"] = np.asarray(jax.random.key_data(key))
        return state

    @staticmethod
    def _sha256_of(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def save(self, step: int, net) -> str:
        state = self._state_of(net)
        checksum = None
        if self._ocp is not None:
            path = os.path.join(self.dir, f"step_{step}")
            ckptr = self._ocp.StandardCheckpointer()
            ckptr.save(path, jax.device_get(state), force=True)
            ckptr.wait_until_finished()
        else:
            path = os.path.join(self.dir, f"step_{step}.npz")
            flat = {}
            leaves = jax.tree_util.tree_leaves_with_path(state)
            for kp, leaf in leaves:
                key = jax.tree_util.keystr(kp)
                flat[key] = np.asarray(leaf)
            # atomic: all bytes land (and reach disk — fsync) under a temp
            # name; the rename publishes a complete file or nothing. The
            # checksum is taken pre-publish so the marker always describes
            # the bytes the save INTENDED — later corruption (torn device,
            # the injected fault below) is caught by restore's verify.
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **flat)
                f.flush()
                os.fsync(f.fileno())
            checksum = self._sha256_of(tmp)
            os.replace(tmp, path)
            if faults.should_fire("checkpoint_torn_write"):
                # chaos (docs/ROBUSTNESS.md): simulate on-disk corruption
                # AFTER the atomic publish — exactly the case the marker
                # checksum + restore fallback exist for
                with open(path, "r+b") as f:
                    f.truncate(max(1, os.path.getsize(path) // 2))
        self._saved.append((step, path, checksum))
        self._write_marker(step, path)
        self._retain()
        observe.metrics().counter("dl4j_tpu_checkpoint_saves_total").inc()
        return path

    def _write_marker(self, step: int, path: str) -> None:
        """Atomic marker update — a crash between checkpoint publish and
        marker write loses the newest entry, never the marker itself."""
        marker = os.path.join(self.dir, "latest.json")
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "path": path,
                       "saved": [[s, p, c] for s, p, c in self._saved]}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)

    def _retain(self):
        if self.keep_last is None:
            return
        while len(self._saved) > self.keep_last:
            _, old, _ = self._saved.pop(0)
            if os.path.isdir(old):
                import shutil

                shutil.rmtree(old, ignore_errors=True)
            elif os.path.exists(old):
                os.remove(old)

    def _load_marker(self):
        marker = os.path.join(self.dir, "latest.json")
        if os.path.exists(marker):
            with open(marker) as f:
                d = json.load(f)
            self._saved = [
                # pre-robustness markers carry [step, path] pairs: keep
                # loading them (checksum None -> restore skips the verify)
                (e[0], e[1], e[2] if len(e) > 2 else None)
                for e in d.get("saved", []) if os.path.exists(e[1])]

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._saved[-1][0] if self._saved else None

    def _verify(self, path: str, checksum: Optional[str]) -> bool:
        """Content integrity: sha256 vs the marker (skip when the entry
        predates checksums or is an orbax directory)."""
        if checksum is None or os.path.isdir(path):
            return True
        try:
            return self._sha256_of(path) == checksum
        except OSError:
            return False

    def restore(self, net, step: Optional[int] = None) -> Optional[int]:
        """Restore into the net (its init() must already have built the
        matching pytree structure). Returns the restored step or None.

        With ``step=None`` candidates are tried NEWEST-FIRST: a checkpoint
        whose checksum mismatches (torn write, disk corruption) or whose
        load raises is skipped with a warning and the next-newest intact
        one is used — corruption costs one save interval, not the run.
        An explicitly requested ``step`` that is corrupt raises (the
        caller asked for those exact bytes)."""
        if not self._saved:
            return None
        if step is None:
            candidates = list(reversed(self._saved))
        else:
            candidates = [next((s, p, c) for s, p, c in self._saved
                               if s == step)]
        newest = candidates[0][0]
        for cand_step, path, checksum in candidates:
            if not self._verify(path, checksum):
                observe.metrics().counter(
                    "dl4j_tpu_checkpoint_corrupt_total").inc()
                if step is not None:
                    raise IOError(
                        f"checkpoint step {cand_step} at {path} failed its "
                        f"integrity check (torn write?)")
                logger.warning(
                    "checkpoint step %d at %s failed its integrity check — "
                    "falling back to the next-newest intact checkpoint",
                    cand_step, path)
                continue
            try:
                restored = self._load_state(net, path)
            except Exception as e:
                observe.metrics().counter(
                    "dl4j_tpu_checkpoint_corrupt_total").inc()
                if step is not None:
                    raise
                logger.warning(
                    "checkpoint step %d at %s failed to load (%r) — "
                    "falling back", cand_step, path, e)
                continue
            if cand_step != newest:
                observe.metrics().counter(
                    "dl4j_tpu_checkpoint_fallback_total").inc()
                observe.log_event("checkpoint_fallback",
                                  wanted=newest, used=cand_step)
            self._apply_state(net, restored)
            return cand_step
        logger.warning(
            "no intact checkpoint found under %s — restore skipped "
            "(training resumes from the net's current state)", self.dir)
        return None

    def _load_state(self, net, path: str) -> Dict[str, Any]:
        target = self._state_of(net)
        if self._ocp is not None and os.path.isdir(path):
            ckptr = self._ocp.StandardCheckpointer()
            return ckptr.restore(path, target=jax.device_get(target))
        data = np.load(path)
        leaves_p = jax.tree_util.tree_leaves_with_path(target)
        restored_leaves = []
        for kp, leaf in leaves_p:
            key = jax.tree_util.keystr(kp)
            if key not in data and key.startswith("['rng_key']"):
                # pre-round-4 checkpoint without the RNG stream: keep
                # the net's current key rather than failing the restore
                restored_leaves.append(np.asarray(leaf))
                continue
            restored_leaves.append(data[key])
        treedef = jax.tree_util.tree_structure(target)
        return jax.tree_util.tree_unflatten(treedef, restored_leaves)

    def _apply_state(self, net, restored: Dict[str, Any]) -> None:
        net.params = jax.tree.map(jnp.asarray, restored["params"])
        net.opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
        net.net_state = jax.tree.map(jnp.asarray, restored["net_state"])
        net.iteration_count = int(restored["iteration"])
        net.epoch_count = int(restored["epoch"])
        if "rng_key" in restored and getattr(net, "_key", None) is not None:
            net._key = jax.random.wrap_key_data(
                jnp.asarray(restored["rng_key"]),
                impl=jax.random.key_impl(net._key))


class CheckpointTrainingListener:
    """Periodic TrainingCheckpointer saves as a listener — the pod-scale
    CheckpointListener."""

    def __init__(self, checkpointer: TrainingCheckpointer, every_n_iterations: int = 100):
        self.ckpt = checkpointer
        self.every = max(1, every_n_iterations)

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.every == 0:
            self.ckpt.save(iteration, model)

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass
