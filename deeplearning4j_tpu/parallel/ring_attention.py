"""Ring attention — sequence/context parallelism over the device mesh.

The reference has NO long-context mechanism beyond truncated BPTT
(SURVEY §6.7); this is the TPU-first capability the rebuild adds as
first-class: sequences sharded across a ``seq`` mesh axis, with K/V blocks
rotating around the ring via ``jax.lax.ppermute`` while each device keeps an
online-softmax accumulator (the FlashAttention recurrence distributed over
ICI — Liu et al. ring attention; blockwise per-hop compute overlaps the
neighbor transfer because XLA pipelines the permute with the matmuls).

Memory per device: O(T/N · d) activations, O((T/N)²) scores per hop — a
sequence N× longer than single-device HBM allows.

Usage (inside shard_map or via the convenience wrapper):
    out = ring_attention(q, k, v, mesh=mesh, axis='seq')   # q,k,v (BH, T, D)
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ring_attention_local(q, k, v, *, axis_name: str, scale: float,
                          causal: bool = False):
    """Per-shard body (runs under shard_map). q/k/v: (BH, T_local, D).

    Each of the N hops computes attention of the LOCAL queries against the
    visiting K/V shard, folded into (acc, m, l) online-softmax state, then
    rotates K/V to the next ring neighbor.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    qf = q.astype(jnp.float32) * scale

    def hop(h, carry):
        acc, m, l, k_cur, v_cur = carry
        # with the (i → i+1) rotation, at hop h device idx holds the kv
        # shard that originated at (idx - h) mod n
        src = jnp.mod(idx - h, n)
        s = jnp.einsum("bqd,bkd->bqk", qf, k_cur.astype(jnp.float32))
        if causal:
            q_pos = idx * t_local + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            k_pos = src * t_local + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 2)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bqk,bkd->bqd", p, v_cur.astype(jnp.float32))
        # rotate kv to the next neighbor (ring over ICI)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc_new, m_new, l_new, k_next, v_next

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((*q.shape[:2], 1), -1e30, jnp.float32)
    l0 = jnp.zeros((*q.shape[:2], 1), jnp.float32)
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, n, hop, (acc0, m0, l0, k, v))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, *, mesh: Mesh, axis: str = "seq",
                   scale: Optional[float] = None, causal: bool = False):
    """Sequence-parallel attention: shard the T axis of (BH, T, D) over
    ``axis`` and run the ring. Returns the full (BH, T, D) output with the
    same sharding."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    # the experimental module keeps the check_rep kwarg this call relies on;
    # jax.shard_map (0.8+) renamed/removed it
    from jax.experimental.shard_map import shard_map

    spec = P(None, axis, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis, scale=scale,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)


class RingSelfAttention:
    """Model-facing wrapper: multi-head self-attention with the sequence
    axis sharded over a mesh (the long-context building block)."""

    def __init__(self, mesh: Mesh, num_heads: int, axis: str = "seq",
                 causal: bool = False):
        self.mesh = mesh
        self.num_heads = num_heads
        self.axis = axis
        self.causal = causal

    def __call__(self, x, wq, wk, wv, wo):
        n, t, d = x.shape
        h = self.num_heads
        dh = d // h

        def split(a):
            return a.reshape(n, t, h, dh).transpose(0, 2, 1, 3).reshape(n * h, t, dh)

        q, k, v = split(x @ wq), split(x @ wk), split(x @ wv)
        out = ring_attention(q, k, v, mesh=self.mesh, axis=self.axis,
                             causal=self.causal)
        out = out.reshape(n, h, t, dh).transpose(0, 2, 1, 3).reshape(n, t, d)
        return out @ wo
