"""Device-mesh data/model-parallel training — the distributed layer.

Reference parity (SURVEY §3.5, §4.4, §6.8):
  * ParallelWrapper (deeplearning4j-scaleout-parallelwrapper): single-node
    multi-device data parallelism — replica per device, AVERAGING or
    SHARED_GRADIENTS exchange through EncodedGradientsAccumulator.
  * SharedTrainingMaster / ParameterAveragingTrainingMaster (dl4j-spark):
    cluster DP — async threshold-compressed gradient sharing over an Aeron
    UDP mesh, or sync parameter averaging via Spark treeAggregate.

TPU-native realization: ONE jitted train step over a ``jax.sharding.Mesh``.
The batch is sharded on the ``data`` axis; params are replicated (DP) or
sharded on ``model`` (TP) via PartitionSpec rules. XLA GSPMD emits the
gradient all-reduce over ICI — there is no accumulator, no threshold codec,
no parameter server on-pod (documented divergence: synchronous bf16
all-reduce replaces Strom-style async sharing; stronger convergence
semantics, SURVEY §3.5). The threshold codec survives in ops/compression.py
as an optional DCN-crossing compressor.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator, ListDataSetIterator

logger = logging.getLogger(__name__)


def make_mesh(axes: Dict[str, int] = None, devices=None) -> Mesh:
    """Build a Mesh from axis sizes, e.g. {'data': 4, 'model': 2}.

    Defaults to all devices on a single 'data' axis (the ParallelWrapper
    shape). The ICI topology mapping is XLA's job; axis ORDER here decides
    which collectives ride the faster inner rings (put 'model' innermost)."""
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": len(devices)})
    total = int(np.prod(list(axes.values())))
    if total != len(devices):
        raise ValueError(f"mesh axes {axes} need {total} devices, have {len(devices)}")
    arr = np.array(devices).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes))


# ---------------------------------------------------------------------------
# Sharding rules (the TP story: regex on param path -> PartitionSpec)
# ---------------------------------------------------------------------------

# Default tensor-parallel rules for our layer param names (Megatron-style
# column/row split pairing so GSPMD inserts ONE all-reduce per block):
#   * attention Wq/Wk/Wv: column-parallel (heads split over 'model');
#     Wo row-parallel (input axis split → psum on the block output)
#   * MLP/dense W: column-parallel on the output-feature axis; W2-style
#     second projections named W2/Wo row-parallel
#   * conv kernels (kh, kw, cin, cout): output-channel split
#   * biases that follow a column-parallel weight: split to match
#   * everything else (norm scales, running stats) replicated
DEFAULT_TP_RULES: List[Tuple[str, P]] = [
    (r".*/(Wq|Wk|Wv|W1)$", P(None, "model")),   # column-parallel
    (r".*/(Wo|W2)$", P("model", None)),          # row-parallel
    (r".*/(bq|bk|bv|b1)$", P("model")),
    (r".*/W$", P(None, None, None, "model")),    # conv HWIO: out channels
    (r".*/RW$", P(None, "model")),
    (r".*", P()),                                 # everything else replicated
]


def moe_ep_rules(axis: str = "expert") -> List[Tuple[str, P]]:
    """Expert-parallel PartitionSpec rules for nn.MoELayer params (leading
    expert axis sharded over ``axis``); prepend to DEFAULT_TP_RULES or use
    alone. GSPMD inserts the dispatch/combine all-to-alls."""
    return [
        (r".*/(We1|We2)$", P(axis, None, None)),
        (r".*/(be1|be2)$", P(axis, None)),
        (r".*/Weg$", P()),
    ]


def _spec_for(path: str, rules: Sequence[Tuple[str, P]]) -> P:
    for pat, spec in rules:
        if re.fullmatch(pat, path):
            return spec
    return P()


def _tree_paths(tree, prefix="") -> List[Tuple[str, Any]]:
    out = []
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.extend(_tree_paths(v, f"{prefix}/{k}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.extend(_tree_paths(v, f"{prefix}/{i}"))
    else:
        out.append((prefix, tree))
    return out


def shard_params(params, mesh: Mesh, rules: Optional[Sequence[Tuple[str, P]]] = None):
    """device_put a param pytree with per-leaf PartitionSpecs.

    With the default rules and a 'model' axis, weight matrices are split on
    the output-feature axis — XLA partitions the matmuls and inserts the TP
    collectives (GSPMD), the role NCCL tensor-parallel code plays elsewhere.
    A leaf whose spec doesn't divide evenly falls back to replication."""
    rules = list(rules or [(r".*", P())])
    flat = _tree_paths(params)
    specs = {}
    for path, leaf in flat:
        spec = _spec_for(path, rules)
        if (len(spec) and len(spec) != np.ndim(leaf)
                and spec[-1] is not None
                and all(a is None for a in spec[:-1])):
            # rank-agnostic last-axis sharding: a rule of the form
            # P(None, ..., axis) means "shard the output-feature (LAST)
            # axis" — adapt it to the leaf's actual rank (dense 2D,
            # Conv1D/locally-connected 3D, conv 4D, Conv3D 5D) instead of
            # silently replicating on rank mismatch
            nd = np.ndim(leaf)
            spec = P(*([None] * (nd - 1) + [spec[-1]])) if nd >= 1 else P()
        # validate divisibility; fall back to replication — LOUDLY, so a
        # mis-sized layer doesn't silently train without TP
        ok = True
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            size = mesh.shape[axis] if isinstance(axis, str) else np.prod(
                [mesh.shape[a] for a in axis])
            if dim >= np.ndim(leaf) or np.shape(leaf)[dim] % size != 0:
                ok = False
        if not ok and spec != P():
            logger.warning(
                "TP: param %s shape %s not divisible by spec %s on mesh %s — "
                "replicating this leaf", path, np.shape(leaf), spec,
                dict(mesh.shape))
        specs[path] = spec if ok else P()

    def put(path_leaf):
        path, leaf = path_leaf
        return jax.device_put(leaf, NamedSharding(mesh, specs[path]))

    placed = {path: put((path, leaf)) for path, leaf in flat}

    def rebuild(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: rebuild(v, f"{prefix}/{k}") for k, v in tree.items()}
        if isinstance(tree, list):
            return [rebuild(v, f"{prefix}/{i}") for i, v in enumerate(tree)]
        if isinstance(tree, tuple):
            return tuple(rebuild(v, f"{prefix}/{i}") for i, v in enumerate(tree))
        return placed[prefix]

    return rebuild(params)


# ---------------------------------------------------------------------------
# ParallelWrapper analog
# ---------------------------------------------------------------------------


class ParallelWrapper:
    """Single-process multi-device data-parallel trainer.

    Reference: org/deeplearning4j/parallelism/ParallelWrapper.java — but
    instead of per-device replica threads + gradient accumulator, the ONE
    jitted step runs SPMD over the mesh. Usage:

        pw = ParallelWrapper(net, mesh=make_mesh({'data': 8}))
        pw.fit(iterator, epochs=3)

    Params/updater state live on the mesh for the duration of fit and are
    written back to the wrapped net (replicated → host view is exact).
    ``tp_rules`` switches selected params to tensor-parallel sharding.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 tp_rules: Optional[Sequence[Tuple[str, P]]] = None,
                 prefetch: int = 2):
        self.net = net
        self.mesh = mesh if mesh is not None else make_mesh()
        self.tp_rules = tp_rules
        self.prefetch = prefetch
        self._is_graph = hasattr(net, "conf") and hasattr(net.conf, "network_inputs")

    def _data_spec(self, arr):
        """Batch-axis sharding; a batch not divisible by the data-axis size
        falls back to replicated (the math is identical under GSPMD, only
        the partitioning differs) — avoids a mid-epoch remainder crash.
        The fallback is LOUD (once): a replicated batch gets no data-
        parallel speedup, which a user sizing batches should know."""
        n = self.mesh.shape["data"]
        if np.shape(arr)[0] % n != 0:
            if not getattr(self, "_warned_ragged", False):
                self._warned_ragged = True
                logger.warning(
                    "ParallelWrapper: batch size %d is not divisible by the "
                    "data axis (%d devices) — this batch runs REPLICATED "
                    "(correct, but no DP speedup). Pad or size batches to a "
                    "multiple of %d.", np.shape(arr)[0], n, n)
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, P("data", *([None] * (np.ndim(arr) - 1))))

    def _place(self, arr):
        """Put a host-local batch onto the mesh. Single-process: device_put
        with the batch-axis sharding. Multi-process (the launcher path):
        each host supplies ITS shard of the global batch and the global
        array assembles via make_array_from_process_local_data — the
        VirtualDataSetIterator per-executor partition, realized as a jax
        global array (global batch = local batch × process_count)."""
        if arr is None:
            return None
        nproc = jax.process_count()
        if nproc == 1:
            a = jnp.asarray(arr)
            return jax.device_put(a, self._data_spec(a))
        a = np.asarray(arr)
        gshape = (a.shape[0] * nproc,) + a.shape[1:]
        if gshape[0] % self.mesh.shape["data"] != 0:
            # ragged remainder batch: mirror the single-process replicated
            # fallback instead of killing the job (which would burn every
            # launcher restart on the same partial batch). All-gather the
            # host shards so every process holds the identical global batch,
            # then run it replicated — same math, no DP speedup, said once.
            if not getattr(self, "_warned_ragged", False):
                self._warned_ragged = True
                logger.warning(
                    "ParallelWrapper: global batch %d (local %d x %d hosts) "
                    "is not divisible by the data axis (%d devices) — this "
                    "batch runs REPLICATED via host all-gather (correct, "
                    "but no DP speedup).", gshape[0], a.shape[0], nproc,
                    self.mesh.shape["data"])
            from jax.experimental import multihost_utils

            global_a = multihost_utils.process_allgather(a)
            return jax.device_put(jnp.asarray(global_a),
                                  NamedSharding(self.mesh, P()))
        sh = NamedSharding(self.mesh, P("data", *([None] * (a.ndim - 1))))
        return jax.make_array_from_process_local_data(sh, a, gshape)

    def _double_buffered(self, data):
        """Place batch i+1 on device BEFORE yielding batch i: device_put is
        asynchronous, so the host→device transfer of the next batch overlaps
        the current step's execution (the round-4 verdict's missing
        double-buffer; AsyncDataSetIterator overlaps host ETL, this overlaps
        the PCIe/ICI copy)."""
        prev = None
        for ds in data:
            cur = (ds, self._place(ds.features), self._place(ds.labels),
                   self._place(ds.features_mask), self._place(ds.labels_mask))
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev

    def lower_step_hlo(self, features, labels) -> str:
        """Compile the sharded train step for one batch and return its HLO —
        the collective-inspection hook (tests assert all-reduce/all-to-all;
        users can eyeball what GSPMD inserted for their mesh/rules)."""
        net = self.net
        step_fn = net._jit_cache.get("train_step")
        if step_fn is None:
            step_fn = net._make_train_step()
            net._jit_cache["train_step"] = step_fn
        rules = self.tp_rules or [(r".*", P())]
        with self.mesh:
            params = shard_params(net.params, self.mesh, rules)
            opt_state = shard_params(net.opt_state, self.mesh, rules)
            net_state = jax.device_put(net.net_state,
                                       NamedSharding(self.mesh, P()))
            x = self._place(np.asarray(features))
            y = self._place(np.asarray(labels))
            args = (params, opt_state, net_state,
                    jnp.asarray(0, jnp.int32), jax.random.key(0))
            if self._is_graph:
                in_name = net.conf.network_inputs[0]
                out_name = net.conf.network_outputs[0]
                args = args + ({in_name: x}, {out_name: y}, None, None)
            else:
                args = args + (x, y, None, None)
            return step_fn.lower(*args).compile().as_text()

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            checkpointer=None, checkpoint_every: int = 0) -> None:
        """``checkpointer`` (parallel.checkpoint.TrainingCheckpointer) +
        ``checkpoint_every`` N iterations enable the periodic-save path the
        multi-process launcher's elasticity relies on: every N steps the
        (replicated) state is pulled back to host and process 0 persists
        it, so a relaunched job resumes mid-fit (SURVEY §6.3/§6.4)."""
        net = self.net
        if isinstance(data, DataSet):
            data = ListDataSetIterator(data, batch_size=batch_size)
        step_fn = net._jit_cache.get("train_step")
        if step_fn is None:
            step_fn = net._make_train_step()
            net._jit_cache["train_step"] = step_fn
        repl = NamedSharding(self.mesh, P())
        rules = self.tp_rules or [(r".*", P())]
        with self.mesh:
            params = shard_params(net.params, self.mesh, rules)
            opt_state = shard_params(net.opt_state, self.mesh, rules)
            net_state = jax.device_put(net.net_state, repl)
            for _ in range(epochs):
                for lst in net.listeners:
                    lst.on_epoch_start(net)
                for ds, x, y, fm, lm in self._double_buffered(data):
                    net.last_batch_size = ds.num_examples()
                    net._key, sub = jax.random.split(net._key)
                    if self._is_graph:
                        in_name = net.conf.network_inputs[0]
                        out_name = net.conf.network_outputs[0]
                        params, opt_state, net_state, loss = step_fn(
                            params, opt_state, net_state,
                            jnp.asarray(net.iteration_count, jnp.int32), sub,
                            {in_name: x}, {out_name: y},
                            None if fm is None else {in_name: fm},
                            None if lm is None else {out_name: lm})
                    else:
                        params, opt_state, net_state, loss = step_fn(
                            params, opt_state, net_state,
                            jnp.asarray(net.iteration_count, jnp.int32), sub,
                            x, y, fm, lm)
                    net._score = loss
                    net.iteration_count += 1
                    if (checkpointer is not None and checkpoint_every
                            and net.iteration_count % checkpoint_every == 0
                            and jax.process_index() == 0):
                        # replicated leaves are addressable on every host,
                        # so the pull-back is local to process 0 — the other
                        # ranks keep streaming steps
                        net.params = jax.device_get(params)
                        net.opt_state = jax.device_get(opt_state)
                        net.net_state = jax.device_get(net_state)
                        checkpointer.save(net.iteration_count, net)
                    for lst in net.listeners:
                        lst.iteration_done(net, net.iteration_count,
                                           net.epoch_count, loss)
                net.epoch_count += 1
                for lst in net.listeners:
                    lst.on_epoch_end(net)
            # write back (host-exact: replicated or gathered shards)
            net.params = jax.device_get(params)
            net.opt_state = jax.device_get(opt_state)
            net.net_state = jax.device_get(net_state)
            net.params = jax.tree.map(jnp.asarray, net.params)
            net.opt_state = jax.tree.map(jnp.asarray, net.opt_state)
            net.net_state = jax.tree.map(jnp.asarray, net.net_state)


class ParallelInference:
    """Multi-device batched serving — ParallelInference.java analog.

    Two modes, mirroring the reference's roles:

    * ``output(x)`` — direct batched call: one jitted forward, batch-sharded
      over the mesh ('data' axis), padded to the axis size.
    * the SERVING loop (``start()`` / ``predict(x)`` / ``stop()``) — the
      reference's request queue + dynamic batching
      (parallelism/ParallelInference.java: observables queued, a dedicated
      thread batches up to ``max_batch`` or ``window_ms``, one model call,
      replies scattered). Here the batch is padded to a FIXED ``max_batch``
      so every call hits one compiled executable, and the single sharded
      forward replaces the reference's per-device replica threads.

    ``predict`` is thread-safe; concurrent clients each get their own rows
    back (tests/test_serving_eval.py runs a multi-threaded throughput gate
    vs per-request calls).
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, *,
                 max_batch: int = 32, window_ms: float = 3.0):
        self.net = net
        self.mesh = mesh if mesh is not None else make_mesh()
        self._is_graph = hasattr(net, "conf") and hasattr(net.conf, "network_inputs")
        self._fn = None
        self.max_batch = int(max_batch)
        self.window_ms = float(window_ms)
        self._queue = None
        self._worker = None
        self._stop = False
        self._placed = None  # (params, net_state) device-resident for serving
        self._obs = None     # serving instruments, resolved once in start()

    # ------------------------------------------------------- generative tier
    @staticmethod
    def generative(model, **engine_kwargs):
        """Facade to the continuous-batching GENERATIVE serving tier
        (docs/SERVING.md): where this class batches stateless forwards in a
        fixed window, a :class:`~deeplearning4j_tpu.serving.GenerativeEngine`
        schedules a decoder model (``models/gpt.py``) at decode-ITERATION
        granularity over a block-paged KV cache — admit/evict mid-flight,
        per-slot sampling. Same lifecycle shape as this class::

            eng = ParallelInference.generative(gpt_model, max_slots=8).start()
            fut = eng.submit(prompt_ids, max_new_tokens=64, temperature=0.8)
            result = fut.result()
            eng.stop()

        ``engine_kwargs`` pass through to ``GenerativeEngine`` (slot
        capacity, page geometry, prompt bucket, seed)."""
        from deeplearning4j_tpu.serving import GenerativeEngine

        return GenerativeEngine(model, **engine_kwargs)

    # ------------------------------------------------------------- serving
    def start(self) -> "ParallelInference":
        import queue as _queue
        import threading

        if self._worker is not None:
            return self
        # resolve the serving instruments ONCE — predict() runs on every
        # client thread and must not take the registry creation lock per
        # request (the train loops hoist theirs the same way)
        m = observe.metrics()
        self._obs = {
            "requests": m.counter("dl4j_tpu_serving_requests_total"),
            "request_h": m.histogram("dl4j_tpu_serving_request_seconds"),
            "wait_h": m.histogram("dl4j_tpu_serving_queue_wait_seconds"),
            "batch_h": m.histogram("dl4j_tpu_serving_batch_seconds"),
            "occupancy_h": m.histogram("dl4j_tpu_serving_batch_occupancy"),
            "batches": m.counter("dl4j_tpu_serving_batches_total"),
            "rows": m.counter("dl4j_tpu_serving_rows_total"),
            "depth": m.gauge("dl4j_tpu_serving_queue_depth"),
        }
        self._queue = _queue.Queue()
        self._stop = False
        # chaos hook (docs/ROBUSTNESS.md): an injected backend failure at
        # server start must surface HERE, synchronously, not as a hung
        # serving loop the first predict() blocks on forever
        faults.maybe_fail("backend_init_fail")
        repl = NamedSharding(self.mesh, P())
        with self.mesh:
            self._placed = (jax.device_put(self.net.params, repl),
                            jax.device_put(self.net.net_state, repl))
        self._worker = threading.Thread(target=self._serve_loop, daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        self._stop = True
        if self._worker is not None:
            self._queue.put(None)  # wake the worker
            self._worker.join(timeout=10)
            self._worker = None
            # fail any still-queued requests so blocked predict() callers
            # wake instead of hanging forever
            import queue as _queue

            while True:
                try:
                    item = self._queue.get_nowait()
                except _queue.Empty:
                    break
                if item is not None and not item[1].done():
                    # graftlife: justified(GR003): ParallelInference futures
                    # are batch-inference calls, not GenerationRequests — the
                    # FINISH_REASONS taxonomy covers the generative stack only
                    item[1].set_exception(
                        RuntimeError("ParallelInference stopped before this "
                                     "request was served"))

    def predict(self, x) -> np.ndarray:
        """Thread-safe single-request inference through the batching queue.
        x: one example (features without the batch dim) or a small batch;
        returns the corresponding output rows.

        Serving telemetry (observe/ — docs/OBSERVABILITY.md): every request
        lands in ``dl4j_tpu_serving_requests_total`` and its full
        enqueue→response latency in the
        ``dl4j_tpu_serving_request_seconds`` histogram (p50/p95/p99),
        recorded on the CLIENT thread — the registry is thread-safe."""
        import time as _time
        from concurrent.futures import Future

        if self._worker is None:
            raise RuntimeError("serving loop not running — call start()")
        x = np.asarray(x)
        fut = Future()
        t0 = _time.perf_counter()
        self._queue.put((x, fut, t0))
        try:
            return fut.result()
        finally:
            # finally: failed requests must still count — an incident is
            # exactly when requests_total and the latency tail matter, and
            # the slowest (failing) requests belong in p99
            self._obs["requests"].inc()
            self._obs["request_h"].observe(_time.perf_counter() - t0)

    def _serve_loop(self) -> None:
        import queue as _queue
        import time as _time

        depth_g = self._obs["depth"]
        while not self._stop:
            try:
                first = self._queue.get(timeout=0.1)
            except _queue.Empty:
                continue
            if first is None:
                continue
            batch = [first]
            rows = first[0].shape[0] if first[0].ndim == self._req_ndim() else 1
            deadline = _time.monotonic() + self.window_ms / 1e3
            while rows < self.max_batch:
                timeout = deadline - _time.monotonic()
                if timeout <= 0:
                    break
                try:
                    item = self._queue.get(timeout=timeout)
                except _queue.Empty:
                    break
                if item is None:
                    continue
                batch.append(item)
                rows += (item[0].shape[0]
                         if item[0].ndim == self._req_ndim() else 1)
            depth_g.set(self._queue.qsize())
            self._run_batch(batch)

    def _req_ndim(self) -> int:
        # batched request rank (single examples arrive with one dim less)
        itype = getattr(self.net.conf, "input_type", None)
        kind = getattr(itype, "kind", "") if itype is not None else ""
        if kind == "convolutional":
            return 4
        if kind == "convolutional3d":
            return 5
        if kind == "recurrent":
            return 3
        return 2

    def _run_batch(self, batch) -> None:
        import time as _time

        try:
            # chaos hook: a backend worker dying mid-batch — the existing
            # contract (every future in the batch gets the exception,
            # the loop survives for the next batch) is what
            # tests/test_robustness.py asserts through this injection
            faults.maybe_fail("backend_init_fail")
            t_dispatch = _time.perf_counter()
            obs = self._obs
            xs, futs, sizes = [], [], []
            for x, fut, t_enq in batch:
                # enqueue→dispatch wait: how long the request sat in the
                # queue before a batch picked it up
                obs["wait_h"].observe(t_dispatch - t_enq)
                xb = x if x.ndim == self._req_ndim() else x[None]
                xs.append(xb)
                futs.append(fut)
                sizes.append(xb.shape[0])
            data = np.concatenate(xs, axis=0)
            n = data.shape[0]
            obs["batches"].inc()
            obs["rows"].inc(n)
            # occupancy: filled rows over the padded slots actually run —
            # a dispatch can exceed max_batch (multi-row requests), so the
            # denominator is the chunked-and-padded total, not one chunk;
            # low occupancy means the padding (not the model) eats the chip
            slots = -(-n // self.max_batch) * self.max_batch
            obs["occupancy_h"].observe(n / slots)
            pad = self.max_batch - (n % self.max_batch or self.max_batch)
            if pad:
                data = np.concatenate(
                    [data, np.repeat(data[-1:], pad, axis=0)], axis=0)
            outs = []
            with self.mesh:
                params, net_state = self._placed
                fn = self._build_fn()
                for i in range(0, data.shape[0], self.max_batch):
                    chunk = jax.device_put(
                        jnp.asarray(data[i:i + self.max_batch]),
                        NamedSharding(self.mesh,
                                      P("data", *([None] * (data.ndim - 1)))))
                    outs.append(np.asarray(fn(params, net_state, chunk)))
            out = np.concatenate(outs, axis=0)[:n]
            t_done = _time.perf_counter()
            obs["batch_h"].observe(t_done - t_dispatch)
            observe.tracer().complete_between(
                "serving_batch", t_dispatch, t_done, category="serving",
                rows=n, requests=len(batch))
            observe.log_event("serving_batch", rows=n, requests=len(batch),
                              batch_seconds=round(t_done - t_dispatch, 6))
            off = 0
            for fut, sz in zip(futs, sizes):
                # graftlife: justified(GR003): batch-inference futures, not
                # GenerationRequests — the FINISH_REASONS taxonomy covers
                # the generative serving stack only
                fut.set_result(out[off:off + sz])
                off += sz
        except Exception as e:  # pragma: no cover - propagate to callers
            for _, fut, _t in batch:
                if not fut.done():
                    fut.set_exception(e)

    def _build_fn(self):
        if self._fn is None:
            net = self.net
            if self._is_graph:
                in_name = net.conf.network_inputs[0]
                out_name = net.conf.network_outputs[0]

                @jax.jit
                def fn(params, net_state, x):
                    acts, _ = net._forward(params, net_state, {in_name: x},
                                           None, train=False, rng=None)
                    return acts[out_name]
            else:
                @jax.jit
                def fn(params, net_state, x):
                    out, _ = net._forward(params, net_state, x, None,
                                          train=False, rng=None)
                    return out

            self._fn = fn
        return self._fn

    def output(self, x) -> np.ndarray:
        net = self.net
        n = self.mesh.shape["data"]
        x = np.asarray(x)
        orig = x.shape[0]
        pad = (-orig) % n
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
        with self.mesh:
            xs = jax.device_put(
                jnp.asarray(x),
                NamedSharding(self.mesh, P("data", *([None] * (x.ndim - 1)))))
            repl = NamedSharding(self.mesh, P())
            params = jax.device_put(net.params, repl)
            net_state = jax.device_put(net.net_state, repl)
            fn = self._build_fn()
            # ledger the sharded forward: the batch is padded to a multiple
            # of the data-mesh size, so a distinct padded batch shape is an
            # honest (and now attributable) new_shape event
            observe.note_jit_signature(
                fn, graph="parallel", key="mesh_output",
                signature=observe.signature_of(x=xs))
            out = fn(params, net_state, xs)
        return np.asarray(out)[:orig]
