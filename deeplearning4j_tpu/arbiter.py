"""Hyperparameter search — the Arbiter module role.

Reference parity (SURVEY §2 "Arbiter(attic)"):
  * arbiter-core ParameterSpace hierarchy (ContinuousParameterSpace,
    IntegerParameterSpace, DiscreteParameterSpace),
  * CandidateGenerator (RandomSearchGenerator, GridSearchCandidateGenerator),
  * ScoreFunction (EvaluationScoreFunction, TestSetLossScoreFunction),
  * OptimizationConfiguration + LocalOptimizationRunner with termination
    conditions (MaxCandidatesCondition, MaxTimeCondition).

TPU-native realization: candidates are plain dicts fed to a user
``model_builder(params) -> net``; each trial is an ordinary jitted
fit/eval on the chip. Sequential trials (one chip, XLA compile cache
shared across same-shaped candidates); the result table is kept so search
curves can feed the UI/stats pipeline.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Parameter spaces (arbiter-core optimize/parameter/*)
# ---------------------------------------------------------------------------


class ParameterSpace:
    def sample(self, rng: np.random.RandomState):
        raise NotImplementedError

    def grid(self, n: int) -> List[Any]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class ContinuousParameterSpace(ParameterSpace):
    """Uniform (or log-uniform) float range — ContinuousParameterSpace.java."""

    low: float
    high: float
    log: bool = False

    def sample(self, rng):
        if self.log:
            return float(math.exp(rng.uniform(math.log(self.low),
                                              math.log(self.high))))
        return float(rng.uniform(self.low, self.high))

    def grid(self, n):
        if self.log:
            return [float(v) for v in np.exp(np.linspace(
                math.log(self.low), math.log(self.high), n))]
        return [float(v) for v in np.linspace(self.low, self.high, n)]


@dataclasses.dataclass(frozen=True)
class IntegerParameterSpace(ParameterSpace):
    """Inclusive int range — IntegerParameterSpace.java."""

    low: int
    high: int

    def sample(self, rng):
        return int(rng.randint(self.low, self.high + 1))

    def grid(self, n):
        return sorted({int(round(v)) for v in
                       np.linspace(self.low, self.high, n)})


@dataclasses.dataclass(frozen=True)
class DiscreteParameterSpace(ParameterSpace):
    """Fixed candidate set — DiscreteParameterSpace.java."""

    values: tuple

    def __init__(self, *values):
        object.__setattr__(self, "values", tuple(values))

    def sample(self, rng):
        return self.values[rng.randint(len(self.values))]

    def grid(self, n):
        return list(self.values)


def _is_space(v):
    return isinstance(v, ParameterSpace)


# ---------------------------------------------------------------------------
# Candidate generators (optimize/generator/*)
# ---------------------------------------------------------------------------


class RandomSearchGenerator:
    """RandomSearchGenerator.java: independent draws from every space."""

    def __init__(self, space: Dict[str, Any], seed: int = 0):
        self.space = space
        self.rng = np.random.RandomState(seed)

    def __iter__(self):
        while True:
            yield {k: (v.sample(self.rng) if _is_space(v) else v)
                   for k, v in self.space.items()}


class GridSearchCandidateGenerator:
    """GridSearchCandidateGenerator.java: cartesian product over per-space
    discretizations (``discretization`` points for continuous ranges)."""

    def __init__(self, space: Dict[str, Any], discretization: int = 3):
        self.space = space
        self.discretization = discretization

    def __iter__(self):
        keys = list(self.space)
        axes = [self.space[k].grid(self.discretization)
                if _is_space(self.space[k]) else [self.space[k]] for k in keys]
        for combo in itertools.product(*axes):
            yield dict(zip(keys, combo))


# ---------------------------------------------------------------------------
# Score functions (optimize/scoring/*)
# ---------------------------------------------------------------------------


def test_set_loss_score(net, data) -> float:
    """TestSetLossScoreFunction: average loss on held-out data (minimize)."""
    total, n = 0.0, 0
    for ds in data:
        total += float(net.score(ds)) * ds.num_examples()
        n += ds.num_examples()
    return total / max(n, 1)


def evaluation_score(metric: str = "accuracy"):
    """EvaluationScoreFunction: negated eval metric so LOWER is better,
    matching the runner's minimization convention."""

    def fn(net, data) -> float:
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation()
        for ds in data:
            ev.eval(ds.labels, net.output(ds.features))
        return -float(getattr(ev, metric)())

    return fn


# ---------------------------------------------------------------------------
# Runner (optimize/runner/LocalOptimizationRunner.java)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TrialResult:
    index: int
    parameters: Dict[str, Any]
    score: float
    duration_s: float
    net: Any = None


class LocalOptimizationRunner:
    """Sequential trial runner with MaxCandidates/MaxTime termination.

    model_builder(params) -> net; fit_fn(net, params) trains it (defaults
    to net.fit over ``train_data`` for ``epochs``); score_fn(net, data) ->
    float, LOWER is better."""

    def __init__(self, model_builder: Callable[[Dict[str, Any]], Any],
                 generator, train_data, score_data=None,
                 score_fn: Callable = test_set_loss_score,
                 epochs: int = 1,
                 max_candidates: int = 10,
                 max_time_s: Optional[float] = None,
                 fit_fn: Optional[Callable] = None,
                 keep_nets: bool = False):
        self.model_builder = model_builder
        self.generator = generator
        self.train_data = train_data
        self.score_data = score_data if score_data is not None else train_data
        self.score_fn = score_fn
        self.epochs = epochs
        self.max_candidates = max_candidates
        self.max_time_s = max_time_s
        self.fit_fn = fit_fn
        self.keep_nets = keep_nets
        self.results: List[TrialResult] = []

    def execute(self) -> TrialResult:
        # monotonic clock for budget/duration math (an NTP step mid-search
        # must not end the run early or corrupt duration_s)
        start = time.perf_counter()
        for idx, params in enumerate(self.generator):
            if idx >= self.max_candidates:
                break
            if self.max_time_s is not None and \
                    time.perf_counter() - start > self.max_time_s:
                break
            t0 = time.perf_counter()
            net = self.model_builder(dict(params))
            if self.fit_fn is not None:
                self.fit_fn(net, dict(params))
            else:
                for _ in range(self.epochs):
                    for ds in self.train_data:
                        net.fit(ds.features, ds.labels)
            score = float(self.score_fn(net, self.score_data))
            self.results.append(TrialResult(
                index=idx, parameters=dict(params), score=score,
                duration_s=time.perf_counter() - t0,
                net=net if self.keep_nets else None))
        if not self.results:
            raise RuntimeError("no candidates were evaluated (empty "
                               "generator or zero budget)")
        return self.best()

    def best(self) -> TrialResult:
        return min(self.results, key=lambda r: r.score)
